"""Lenia: the continuous-CA rule family (docs/RULES.md).

Where every discrete rule maps ``(state, integer count) -> state``
through a LUT, Lenia (Chan 2019) runs a *smooth* world: float32 boards
in [0, 1], a radially symmetric weighted kernel, a smooth growth
function, and a clipped Euler update::

    A' = clip(A + dt * G(K (*) A), 0, 1)
    G(u) = 2 * exp(-(u - mu)^2 / (2 sigma^2)) - 1

The kernel is the classic shell construction: with normalized polar
radius ``rho = |d| / R`` and ring amplitudes ``b`` (``B = len(b)``
shells), ``K(rho) = b[floor(B rho)] * core(B rho mod 1)`` where
``core(x) = exp(4 - 1/(x (1 - x)))`` — a smooth bump peaking mid-shell,
zero at both shell edges (and at the center).  ``K`` is normalized to
sum 1 so the correlation is a weighted mean and ``G`` sees [0, 1].

This is exactly the workload the banded-matmul neighborhoods
(``ops.conv``) exist for: the kernel is weighted, wide (the ``orbium``
preset is radius 13 — a 27x27 stencil the roll path would unroll into
~700 shifted adds) and float32, so ``K (*) A`` runs as a handful of MXU
matmul pairs.  :class:`LeniaRule` is a frozen :class:`Rule` subclass,
so the whole serving stack — CompileKey grouping, vmapped engines,
spill/resume, the gateway — carries it exactly like ``ising`` rode in
as a rule subclass (PR 6); the board dtype ("float32") rides in the
CompileKey, and the numpy roll executor is the pinned oracle
(``tests/fixtures/lenia_kat.json`` holds its golden vectors).

Float determinism contract (docs/RULES.md): the numpy roll oracle is
byte-stable and KAT-pinned; the jax paths (roll and matmul) agree with
it to ``allclose`` tolerance only — float summation order is executor-
specific.  Anything that must be byte-exact (the CI gateway
byte-compare, golden vectors) therefore runs the numpy executor.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from functools import cached_property

import numpy as np

from tpu_life.models.rules import Rule

#: Executors carrying the float32 board path.  The single allow-list —
#: runner factory, serve engine factory and driver pre-check all
#: consult it (the ``mc.SUPPORTED_BACKENDS`` pattern).  The sharded
#: multi-device backend keeps the board float32 end to end (torus
#: boundary only — backends.sharded_backend raises the precise reason
#: otherwise), as does the serve mesh tier built on it (its CompileKey
#: backend is the ``mesh:RxC`` family, checked by prefix below).
SUPPORTED_BACKENDS = ("jax", "numpy", "sharded")

#: allclose tolerance between float executors (numpy oracle vs the jax
#: roll/matmul paths).  Stated, tested, and documented in docs/RULES.md:
#: per-step error is summation-order-level (~1e-7) and the clipped
#: update keeps it from compounding past this over KAT-length runs.
FLOAT_ATOL = 1e-4


def require_float_path(rule: Rule, backend_name: str) -> None:
    """The hard gate: continuous rules only run on float executors.
    A silent int8 cast would quantize the board to junk — worse than
    an error."""
    if backend_name not in SUPPORTED_BACKENDS and not backend_name.startswith(
        "mesh:"
    ):
        raise ValueError(
            f"continuous rule {rule.name!r} needs the jax or numpy "
            f"backend (float32 boards; {backend_name!r} has no float "
            f"path) — a quantized fallback would not be the rule you "
            f"asked for"
        )


@dataclass(frozen=True)
class LeniaRule(Rule):
    """A Lenia world as a frozen, hashable rule value.

    The inherited ``birth``/``survive``/``states`` fields are unused
    (the transition is the growth function, not a count LUT); they keep
    their defaults so the rule hashes and serializes like any other.
    ``boundary`` defaults to the torus (the standard Lenia world) but
    the clamped variant is legal — the kernel truncates at the edges
    exactly like a clamped count stencil.
    """

    name: str = "lenia"
    radius: int = 13
    mu: float = 0.15  # growth-function center
    sigma: float = 0.017  # growth-function width
    dt: float = 0.1  # Euler step size
    peaks: tuple = (1.0,)  # ring (shell) amplitudes, center outward
    boundary: str = "torus"

    def __post_init__(self):
        super().__post_init__()
        if not (0.0 < float(self.mu) < 1.0):
            raise ValueError(f"lenia mu must be in (0, 1), got {self.mu}")
        if not (0.0 < float(self.sigma) < 1.0):
            raise ValueError(
                f"lenia sigma must be in (0, 1), got {self.sigma}"
            )
        if not (0.0 < float(self.dt) <= 1.0):
            raise ValueError(f"lenia dt must be in (0, 1], got {self.dt}")
        if not self.peaks or any(
            not (0.0 <= float(b) <= 1.0) for b in self.peaks
        ):
            raise ValueError(
                f"lenia ring amplitudes must be a non-empty tuple in "
                f"[0, 1], got {self.peaks!r}"
            )
        if max(float(b) for b in self.peaks) <= 0.0:
            raise ValueError("lenia needs at least one nonzero ring")

    @property
    def continuous(self) -> bool:
        return True

    @cached_property
    def kernel(self) -> np.ndarray:
        """The normalized float32 shell kernel, ``(2r+1, 2r+1)``."""
        r = self.radius
        dy, dx = np.mgrid[-r : r + 1, -r : r + 1].astype(np.float64)
        rho = np.sqrt(dy * dy + dx * dx) / r
        nb = len(self.peaks)
        srho = rho * nb
        shell = np.minimum(np.floor(srho), nb - 1)
        frac = srho - shell
        with np.errstate(divide="ignore", over="ignore"):
            core = np.where(
                (frac > 0.0) & (frac < 1.0),
                np.exp(4.0 - 1.0 / np.maximum(frac * (1.0 - frac), 1e-12)),
                0.0,
            )
        amp = np.asarray(self.peaks, np.float64)[shell.astype(np.int64)]
        k = np.where(rho < 1.0, amp * core, 0.0)
        total = k.sum()
        if total <= 0.0:
            raise ValueError(
                f"lenia kernel for {self.name!r} is degenerate (all-zero "
                f"after the shell construction)"
            )
        return (k / total).astype(np.float32)


# -- the step ---------------------------------------------------------------
def growth(xp, u, rule: LeniaRule):
    """The smooth growth field ``G(u)`` in [-1, 1]."""
    mu = xp.float32(rule.mu)
    inv2s2 = xp.float32(1.0 / (2.0 * float(rule.sigma) ** 2))
    d = u - mu
    return xp.float32(2.0) * xp.exp(-(d * d) * inv2s2) - xp.float32(1.0)


def _make_roll_conv(xp, rule: LeniaRule, shape: tuple[int, int]):
    """The weighted roll path: the kernel unrolled into shifted-scaled
    adds over a padded board — the oracle shape (numpy) and the
    below-crossover executor.  O(nnz(K)) slices per step."""
    h, w = int(shape[0]), int(shape[1])
    r = rule.radius
    kern = rule.kernel
    offsets = [
        (dy, dx, float(kern[dy + r, dx + r]))
        for dy in range(-r, r + 1)
        for dx in range(-r, r + 1)
        if kern[dy + r, dx + r] != 0.0
    ]
    mode = "wrap" if rule.boundary == "torus" else "constant"

    def conv(a):
        padded = xp.pad(a, ((r, r), (r, r)), mode=mode)
        out = None
        for dy, dx, wgt in offsets:
            sl = padded[r + dy : r + dy + h, r + dx : r + dx + w] * xp.float32(
                wgt
            )
            out = sl if out is None else out + sl
        return out

    return conv


def make_lenia_step(
    xp, rule: LeniaRule, shape: tuple[int, int], stencil: str = "matmul"
):
    """One Lenia step ``f32[h, w] -> f32[h, w]``, pure and traceable.

    ``stencil`` picks the correlation executor: ``matmul`` builds the
    banded operators once (``ops.conv`` — the MXU path), ``roll`` the
    unrolled shifted adds (the oracle shape).
    """
    if stencil == "matmul":
        from tpu_life.ops.conv import make_conv

        conv = make_conv(xp, shape, rule.kernel, rule.boundary)
    else:
        conv = _make_roll_conv(xp, rule, shape)
    dt = float(rule.dt)

    def step(board):
        u = conv(board.astype(xp.float32))
        a = board + xp.float32(dt) * growth(xp, u, rule)
        return xp.clip(a, xp.float32(0.0), xp.float32(1.0)).astype(
            xp.float32
        )

    return step


def step_np(
    board: np.ndarray, rule: LeniaRule, stencil: str = "roll"
) -> np.ndarray:
    """One ground-truth numpy step (roll by default — the KAT oracle)."""
    return make_lenia_step(np, rule, board.shape, stencil)(
        np.asarray(board, np.float32)
    )


def run_np(
    board: np.ndarray, rule: LeniaRule, steps: int, stencil: str = "roll"
) -> np.ndarray:
    """``steps`` oracle steps — what serve results are byte-compared to
    (on the numpy executor) and allclose-compared to (jax paths)."""
    fn = make_lenia_step(np, rule, board.shape, stencil)
    board = np.asarray(board, np.float32)
    for _ in range(steps):
        board = fn(board)
    return board


def validate_board(board: np.ndarray, rule: LeniaRule) -> np.ndarray:
    """Submit-time float-board validation shared by every front: 2-D,
    finite, within [0, 1]; returns the float32 copy the engines step."""
    board = np.asarray(board)
    if board.ndim != 2:
        raise ValueError(f"board must be 2-D, got shape {board.shape}")
    b = board.astype(np.float32)
    if not np.isfinite(b).all():
        raise ValueError(
            f"continuous rule {rule.name!r} needs a finite board; found "
            f"NaN or Inf"
        )
    lo, hi = float(b.min(initial=0.0)), float(b.max(initial=0.0))
    if lo < 0.0 or hi > 1.0:
        raise ValueError(
            f"continuous rule {rule.name!r} needs board values in "
            f"[0, 1]; found {lo if lo < 0.0 else hi}"
        )
    return b


def seeded_board(
    height: int, width: int, density: float = 0.5, *, seed: int = 0
) -> np.ndarray:
    """A seeded float32 board from the counter-based stream: each cell
    alive with probability ``density`` carrying a uniform [0, 1)
    magnitude, dead (0.0) otherwise.  Identical on every host — the
    continuous twin of ``mc.prng.seeded_board``, same ``SUB_BOARD``
    substream, so the stamped seed fully replays the run."""
    from tpu_life.mc import prng

    if not 0.0 <= density <= 1.0:
        raise ValueError(f"density must be in [0, 1], got {density}")
    k0, k1 = prng.key_halves(seed)
    mask_u = prng.cell_uniforms(
        np, (height, width), k0, k1, np.uint32(0), prng.SUB_BOARD
    )
    mag_u = prng.cell_uniforms(
        np, (height, width), k0, k1, np.uint32(1), prng.SUB_BOARD
    )
    alive = (
        np.ones((height, width), bool)
        if density >= 1.0
        else mask_u < np.uint32(prng.threshold_u32(density))
    )
    mag = (mag_u.astype(np.float64) * (1.0 / 4294967296.0)).astype(np.float32)
    return np.where(alive, mag, np.float32(0.0)).astype(np.float32)


# -- runners (the driver path) ----------------------------------------------
class LeniaHostRunner:
    """NumPy Runner — the ground truth behind ``run --rule lenia:*``."""

    def __init__(self, board: np.ndarray, rule: LeniaRule, *, stencil="roll"):
        self.board = validate_board(board, rule)
        self._fn = make_lenia_step(np, rule, self.board.shape, stencil)

    def advance(self, steps: int) -> None:
        for _ in range(steps):
            self.board = self._fn(self.board)

    def sync(self) -> None:
        pass

    def fetch(self) -> np.ndarray:
        return self.board

    def snapshot(self):
        return lambda board=self.board: board

    def live_count(self) -> int:
        # the discrete notion degrades gracefully: cells above one half
        return int(np.count_nonzero(self.board >= 0.5))


class LeniaDeviceRunner:
    """Single-device XLA Runner: fused float scan, donated buffers."""

    def __init__(
        self,
        board: np.ndarray,
        rule: LeniaRule,
        *,
        stencil: str = "matmul",
        device=None,
    ):
        import jax
        import jax.numpy as jnp

        board = validate_board(board, rule)
        self.x = jax.device_put(jnp.asarray(board, jnp.float32), device)
        step = make_lenia_step(jnp, rule, board.shape, stencil)

        def advance(x, *, steps):
            def body(b, _):
                return step(b), None

            x, _ = jax.lax.scan(body, x, None, length=steps)
            return x

        self._advance = jax.jit(
            advance, static_argnames=("steps",), donate_argnums=0
        )

    def advance(self, steps: int) -> None:
        if steps > 0:
            self.x = self._advance(self.x, steps=steps)

    def sync(self) -> None:
        import jax

        jax.block_until_ready(self.x)
        np.asarray(self.x[:1, :1])

    def fetch(self) -> np.ndarray:
        return np.asarray(self.x)

    def snapshot(self):
        # valid until the next advance donates the buffer — materialize
        # within the chunk callback, matching DeviceRunner's contract
        return lambda x=self.x: np.asarray(x)

    def live_count(self) -> int:
        return int(np.count_nonzero(np.asarray(self.x) >= 0.5))


def lenia_runner_for(backend, board: np.ndarray, rule: LeniaRule):
    """Runner factory for continuous rules, dispatched on the backend —
    the float twin of ``mc.engine.mc_runner_for``.  The backend's
    resolved stencil mode routes the correlation executor; numpy under
    ``auto`` stays the roll oracle (``ops.conv.resolve_stencil``)."""
    from tpu_life.ops.conv import resolve_stencil

    name = getattr(backend, "name", "") or type(backend).__name__
    require_float_path(rule, name)
    stencil = resolve_stencil(
        rule, getattr(backend, "stencil", "auto"), name
    )
    if name == "jax":
        return LeniaDeviceRunner(
            board,
            rule,
            stencil=stencil,
            device=getattr(backend, "device", None),
        )
    return LeniaHostRunner(board, rule, stencil=stencil)


# -- the spec grammar -------------------------------------------------------
#: Named presets (docs/RULES.md).  ``orbium`` is the classic glider's
#: parameter point (R13, mu 0.15, sigma 0.017, dt 0.1, one ring);
#: ``mini`` is a cheap small-kernel world sized for tests and CI smoke.
PRESETS: dict[str, dict] = {
    "orbium": dict(radius=13, mu=0.15, sigma=0.017, dt=0.1, peaks=(1.0,)),
    "mini": dict(radius=4, mu=0.15, sigma=0.04, dt=0.25, peaks=(1.0,)),
}

_FIELD_RE = re.compile(r"^(dt|[RMSB])(.*)$", re.IGNORECASE)


def parse_lenia(spec: str) -> LeniaRule:
    """``lenia`` / ``lenia:<preset>`` / parametric
    ``lenia:R<r>,m<mu>,s<sigma>[,dt<dt>][,b<a1;a2;...>]`` (+ optional
    ``:T`` torus suffix — the default topology anyway) with typed
    errors for every malformation, mirroring :func:`parse_rule`.
    """
    raw = spec.strip()
    body = raw[len("lenia"):].lstrip(":").strip()
    boundary = "torus"
    m_t = re.search(r":\s*[tT]\s*$", body)
    if m_t is not None:
        body = body[: m_t.start()].strip()
    elif body.lower() == "t":
        # the bare 'lenia:T' form: the suffix with no body — the default
        # preset on its (already default) torus
        body = ""
    if not body:
        return LeniaRule(name="lenia:orbium", **PRESETS["orbium"])
    key = body.lower().replace("-", "_")
    if key in PRESETS:
        return LeniaRule(name=f"lenia:{key}", **PRESETS[key])
    if not body.startswith(("R", "r")):
        # not a preset and not parametric: reject loudly with the menu
        raise ValueError(
            f"unknown lenia spec {spec!r}: presets are "
            f"{sorted(PRESETS)}, or parametric "
            f"'lenia:R<r>,m<mu>,s<sigma>[,dt<dt>][,b<a1;a2;...>]'"
        )
    fields: dict[str, str] = {}
    for part in body.split(","):
        part = part.strip()
        m = _FIELD_RE.match(part)
        if not m:
            raise ValueError(f"bad lenia field {part!r} in {spec!r}")
        k, v = m.group(1), m.group(2)
        k = "R" if k.lower() == "r" else k.lower()
        if k in fields:
            raise ValueError(f"duplicate lenia field {k!r} in {spec!r}")
        fields[k] = v
    if "R" not in fields:
        raise ValueError(f"lenia spec {spec!r} needs a radius field R<r>")
    try:
        radius = int(fields["R"])
        mu = float(fields.get("m", "0.15"))
        sigma = float(fields.get("s", "0.017"))
        dt = float(fields.get("dt", "0.1"))
        peaks = tuple(
            float(b) for b in fields.get("b", "1").split(";") if b.strip()
        )
    except ValueError:
        raise ValueError(
            f"bad lenia parameter value in {spec!r} (fields: R=int, "
            f"m/s/dt=float, b=floats joined by ';')"
        ) from None
    return LeniaRule(
        name=raw,
        radius=radius,
        mu=mu,
        sigma=sigma,
        dt=dt,
        peaks=peaks,
        boundary=boundary,
    )
