from tpu_life.models.rules import (
    Rule,
    parse_rule,
    get_rule,
    register_rule,
    RULE_REGISTRY,
)
from tpu_life.models import patterns

__all__ = [
    "Rule",
    "parse_rule",
    "get_rule",
    "register_rule",
    "RULE_REGISTRY",
    "patterns",
]
