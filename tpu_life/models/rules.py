"""Rules as data: the framework's "model" layer.

The reference hard-codes (a buggy rendition of) Conway B3/S23 in branchy C++
(Parallel_Life_MPI.cpp:37-54 — see SURVEY.md §2.2 for the rule-overwrite
analysis).  Here a rule is a small immutable value — (birth set, survive set,
radius, state count) — from which the ops layer builds branch-free lookup
tables that XLA fuses into the stencil.  One engine covers:

- life-like rules (``B3/S23`` Conway, ``B36/S23`` HighLife,
  ``B3678/S34678`` Day & Night, ...): 2 states, radius 1;
- Generations rules (``B2/S/C3`` Brian's Brain, ...): ``states > 2`` adds
  refractory decay states 2..states-1 that count as dead but block birth;
- Larger-than-Life (``R5,C2,S34..58,B34..45`` Bugs, ...): ``radius > 1``
  widens the neighborhood; counts stay exact in int32.  The ``N`` field
  picks its shape: ``NM`` (default) = the ``(2r+1)^2`` Moore box, ``NN`` =
  the ``|dx|+|dy| <= r`` von Neumann diamond.

Semantics (synchronous update; boundary per ``Rule.boundary`` — "clamped"
dead edges, the reference's non-periodic world (Parallel_Life_MPI.cpp:21-27),
or a board-sized "torus" via the Golly ``:T`` suffix):

- ``count`` = number of *alive* (state == 1) cells in the rule's
  neighborhood (Moore box or von Neumann diamond per ``neighborhood``;
  center excluded unless ``include_center``).
- dead (0):  -> 1 if ``count in birth`` else 0
- alive (1): -> 1 if ``count in survive`` else (2 if states > 2 else 0)
- dying (s >= 2, Generations only): -> s + 1, wrapping to 0 at ``states``
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass, field
from functools import cached_property

import numpy as np


@dataclass(frozen=True)
class Rule:
    name: str
    birth: frozenset = field(default_factory=frozenset)
    survive: frozenset = field(default_factory=frozenset)
    radius: int = 1
    states: int = 2
    include_center: bool = False  # LtL "M1" variants count the center cell
    # Golly "N" field: "moore" = the (2r+1)^2 box (the reference's 8-cell
    # scan at r=1, Parallel_Life_MPI.cpp:19-31), "von_neumann" = the
    # |dx|+|dy| <= r diamond
    neighborhood: str = "moore"
    # world topology: "clamped" = the reference's dead non-periodic edges
    # (Parallel_Life_MPI.cpp:21-27); "torus" = periodic wraparound (the
    # Golly ":T" bounded-grid suffix, board-sized)
    boundary: str = "clamped"

    def __post_init__(self):
        if self.radius < 1:
            raise ValueError(f"radius must be >= 1, got {self.radius}")
        if not (2 <= self.states <= 10):
            # 10-state ceiling keeps the disk codec single-digit ('0'..'9').
            raise ValueError(f"states must be in [2, 10], got {self.states}")
        if self.neighborhood not in ("moore", "von_neumann"):
            raise ValueError(
                f"neighborhood must be 'moore' or 'von_neumann', "
                f"got {self.neighborhood!r}"
            )
        if self.boundary not in ("clamped", "torus"):
            raise ValueError(
                f"boundary must be 'clamped' or 'torus', got {self.boundary!r}"
            )
        mc = self.max_count
        for s in self.birth | self.survive:
            if not (0 <= s <= mc):
                raise ValueError(f"count {s} out of range [0, {mc}] for radius {self.radius}")

    @property
    def max_count(self) -> int:
        r = self.radius
        if self.neighborhood == "von_neumann":
            size = 2 * r * (r + 1) + 1  # the diamond, center included
        else:
            size = (2 * r + 1) ** 2
        return size - (0 if self.include_center else 1)

    @cached_property
    def tables(self) -> tuple[np.ndarray, np.ndarray]:
        """(birth_table, survive_table): int8[max_count + 1] 0/1 masks."""
        n = self.max_count + 1
        birth = np.zeros(n, dtype=np.int8)
        survive = np.zeros(n, dtype=np.int8)
        birth[sorted(self.birth)] = 1
        survive[sorted(self.survive)] = 1
        return birth, survive

    @cached_property
    def transition_table(self) -> np.ndarray:
        """Full LUT: int8[states, max_count + 1] -> next state.

        Row s, column c = next state of a cell in state s with c live
        neighbors.  This is the single source of truth the NumPy, XLA and
        Pallas kernels all index into — one table, three executors.
        """
        birth, survive = self.tables
        n = self.max_count + 1
        t = np.zeros((self.states, n), dtype=np.int8)
        t[0] = birth  # dead -> birth mask
        if self.states == 2:
            t[1] = survive
        else:
            t[1] = np.where(survive == 1, 1, 2).astype(np.int8)
            for s in range(2, self.states):
                t[s] = (s + 1) % self.states
        return t

    @property
    def stochastic(self) -> bool:
        """True for Monte-Carlo rules whose step consumes counter-based
        PRNG draws (see ``tpu_life.mc``); they carry a per-run seed and
        only run on executors that honor the key schedule."""
        return False

    @property
    def continuous(self) -> bool:
        """True for continuous-state CA (``tpu_life.models.lenia``):
        float32 boards in [0, 1], a weighted kernel instead of a count
        LUT, and an Euler update instead of a transition table.  They
        run only on executors with a float path (jax / numpy)."""
        return False

    @property
    def board_dtype(self) -> str:
        """The board element dtype this rule steps ("int8" for every
        discrete rule; "float32" on the continuous tier) — what the
        serve CompileKey and the codecs key on."""
        return "float32" if self.continuous else "int8"

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class IsingRule(Rule):
    """The 2-D Ising model under Metropolis–Hastings (J = 1, H = 0).

    Spins live on the board as int8 {0, 1} <-> {-1, +1}; one CA "step" is
    one full Metropolis **sweep** via the checkerboard decomposition (two
    half-lattice updates — cells of one (row+col) parity see only
    frozen cells of the other, so the vectorized update is exactly
    sequential single-site Metropolis within a parity).  Temperature is
    NOT part of the rule: it is a per-session scalar (serve packs mixed
    temperatures into one CompileKey); the rule itself stays a frozen
    hashable value like every other ``Rule``.

    The inherited fields pin the neighborhood structure: radius-1 von
    Neumann (the 4-neighbor coupling), 2 states, torus topology (the
    periodic lattice Onsager's solution assumes).  ``birth``/``survive``
    are unused — the transition is the Metropolis acceptance rule in
    ``tpu_life.mc.ising``, not a count LUT.
    """

    name: str = "ising"
    radius: int = 1
    states: int = 2
    neighborhood: str = "von_neumann"
    boundary: str = "torus"

    @property
    def stochastic(self) -> bool:
        return True


@dataclass(frozen=True)
class NoisyRule(Rule):
    """A registered 2-state rule composed with per-cell flip noise.

    Spec ``noisy:<p>/<base>``: apply ``base`` deterministically, then
    flip each cell 0<->1 with probability ``flip_p`` from the counter
    stream's ``SUB_NOISE`` substream.  The base rule's structural fields
    (birth/survive/radius/neighborhood/boundary) are copied onto this
    rule, so the deterministic half reuses the exact stencil machinery
    (``ops.stencil.make_step`` / ``ops.reference.step_np``) unchanged;
    ``base`` is kept for provenance.  ``flip_p`` is frozen in the rule
    (it is part of the spec string and hence the CompileKey), unlike the
    ising temperature which rides per-session.
    """

    flip_p: float = 0.0
    base: Rule | None = None

    def __post_init__(self):
        super().__post_init__()
        if not (0.0 <= self.flip_p <= 1.0):
            raise ValueError(
                f"noise probability must be in [0, 1], got {self.flip_p}"
            )
        if self.states != 2:
            raise ValueError(
                f"noisy rules need a 2-state base (flip is 0<->1); "
                f"{self.base.name if self.base else self.name!r} has "
                f"{self.states} states"
            )

    @property
    def stochastic(self) -> bool:
        return True


def _parse_noisy(spec: str) -> NoisyRule:
    """``noisy:<p>/<base>`` -> :class:`NoisyRule`, with typed errors for
    every malformation (mirroring :func:`parse_rule`'s loud failures)."""
    body = spec[len("noisy:"):]
    if "/" not in body:
        raise ValueError(
            f"bad noisy spec {spec!r}: expected 'noisy:<p>/<base>' "
            f"(e.g. 'noisy:0.01/conway')"
        )
    p_str, base_spec = body.split("/", 1)
    try:
        p = float(p_str)
    except ValueError:
        raise ValueError(
            f"bad noise probability {p_str!r} in {spec!r}: not a number"
        ) from None
    if not np.isfinite(p) or not (0.0 <= p <= 1.0):
        raise ValueError(
            f"noise probability must be in [0, 1], got {p_str!r} in {spec!r}"
        )
    if not base_spec.strip():
        raise ValueError(f"bad noisy spec {spec!r}: empty base rule")
    base = parse_rule(base_spec)
    if base.stochastic:
        raise ValueError(
            f"noisy base must be deterministic, got stochastic rule "
            f"{base.name!r} in {spec!r} (substream composition of two "
            f"stochastic rules is not defined)"
        )
    if base.continuous:
        raise ValueError(
            f"noisy base must be a discrete rule, got continuous rule "
            f"{base.name!r} in {spec!r} (a 0<->1 flip is meaningless on "
            f"float boards)"
        )
    # a multi-state base is rejected by NoisyRule.__post_init__ (the one
    # check that also guards direct construction)
    return NoisyRule(
        name=f"noisy:{p_str}/{base.name}",
        birth=base.birth,
        survive=base.survive,
        radius=base.radius,
        states=base.states,
        include_center=base.include_center,
        neighborhood=base.neighborhood,
        boundary=base.boundary,
        flip_p=p,
        base=base,
    )


class GeometryError(ValueError):
    """A rule whose kernel cannot fit the board it was submitted with.

    Raised by :func:`validate_rule_geometry` and caught TYPED at every
    admission front — ``run``/``sweep`` exit 2, serve submit rejects
    before anything is stored, the gateway answers 400
    ``radius_too_large`` — instead of surfacing as a downstream shape
    (or silently wrong torus double-count) error.
    """


def validate_rule_geometry(rule: Rule, shape: tuple[int, int]) -> None:
    """Reject a kernel larger than the board: ``2r + 1 > min(h, w)``.

    ``parse_rule`` accepts any ``R<r>`` Larger-than-Life radius (and the
    continuous tier any kernel radius), but a kernel wider than the
    board is never the simulation the client asked for: clamped boards
    degenerate, torus neighborhoods would alias around the wrap seam.
    Radius-1 rules are exempt — thin boards (1xN stripes, 2x2 toys) are
    long-standing legal inputs with well-defined reference semantics.
    """
    r = int(rule.radius)
    if r <= 1:
        return
    h, w = int(shape[0]), int(shape[1])
    if 2 * r + 1 > min(h, w):
        raise GeometryError(
            f"rule {rule.name!r} has kernel diameter {2 * r + 1} "
            f"(radius {r}) but the board is only {h}x{w}; the kernel "
            f"must fit the board (2r+1 <= min(h, w)) — shrink the "
            f"radius or grow the board"
        )


def _expand_ranges(spec: str) -> frozenset:
    """Expand '34..58' / '2,3,5..7' style count specs into a set of ints."""
    out = set()
    if not spec:
        return frozenset(out)
    for part in spec.split(","):
        if ".." in part:
            lo, hi = part.split("..")
            out.update(range(int(lo), int(hi) + 1))
        elif part:
            out.add(int(part))
    return frozenset(out)


_BS_RE = re.compile(r"^B(?P<b>\d*)/S(?P<s>\d*)(?:/C(?P<c>\d+))?$", re.IGNORECASE)
_SB_RE = re.compile(r"^(?P<s>\d*)/(?P<b>\d*)(?:/(?P<c>\d+))?$")


def parse_rule(spec: str) -> Rule:
    """Parse a rule string into a :class:`Rule`.

    Accepted formats:
    - named rules from the registry: ``conway``, ``highlife``, ...
    - B/S (optionally Generations): ``B3/S23``, ``B36/S23``, ``B2/S/C3``
    - S/B classic: ``23/3``, ``345/2/4``
    - Larger-than-Life (Golly-style): ``R5,C2,M0,S34..58,B34..45[,NM|NN]``
      (C = states, M = include center, N = neighborhood: NM Moore box /
      NN von Neumann diamond; C, M and N optional)
    - any of the above + Golly's bounded-grid suffix ``:T`` for a
      board-sized torus (periodic wraparound): ``conway:T``, ``B3/S23:T``
    - stochastic rules (``tpu_life.mc``): ``ising`` (Metropolis,
      per-session temperature) and ``noisy:<p>/<base>`` (per-cell flip
      probability ``p`` over any registered 2-state rule):
      ``noisy:0.01/conway``, ``noisy:0.05/B36/S23:T``
    - continuous rules (``tpu_life.models.lenia``, docs/RULES.md):
      ``lenia`` / ``lenia:<preset>`` / parametric
      ``lenia:R<r>,m<mu>,s<sigma>[,dt<dt>][,b<a1;a2;...>]`` — float32
      boards, weighted-kernel correlation, smooth growth
    """
    spec = spec.strip()
    if spec.lower().startswith("noisy:"):
        # before the ':T' scan: the noisy prefix's own colon must not be
        # mistaken for a bounded-grid suffix; the base spec inside may
        # still carry ':T' (parsed recursively)
        return _parse_noisy(spec)
    if spec.lower() == "lenia" or spec.lower().startswith("lenia:"):
        # the continuous tier (docs/RULES.md): lenia presets and the
        # parametric spec own their colon grammar, like noisy: does
        from tpu_life.models.lenia import parse_lenia

        return parse_lenia(spec)
    m_t = re.search(r":\s*[tT](.*)$", spec)
    if m_t is not None:
        dims = m_t.group(1).strip()
        if dims:
            raise ValueError(
                f"bounded-grid dimensions {dims!r} are unsupported: the "
                f"torus is board-sized (use plain ':T')"
            )
        base = parse_rule(spec[: m_t.start()])
        return dataclasses.replace(
            base, name=f"{base.name}:T", boundary="torus"
        )
    key = spec.lower().replace("-", "_").replace(" ", "_")
    if key in RULE_REGISTRY:
        return RULE_REGISTRY[key]

    if spec.upper().startswith("R") and "," in spec:
        fields = {}
        for part in spec.split(","):
            part = part.strip()
            m = re.match(r"^([A-Za-z])(.*)$", part)
            if not m:
                raise ValueError(f"bad LtL field {part!r} in rule {spec!r}")
            k, v = m.group(1).upper(), m.group(2)
            if k in ("S", "B"):
                fields[k] = fields.get(k, "") + ("," if k in fields else "") + v
            else:
                fields[k] = v
        radius = int(fields.get("R", 1))
        states = int(fields.get("C", "2") or "2")
        states = max(states, 2)  # Golly uses C0/C1 for plain 2-state
        nb_field = fields.get("N", "M").upper()
        if nb_field in ("M", ""):
            neighborhood = "moore"
        elif nb_field == "N":
            neighborhood = "von_neumann"
        else:
            # rejected loudly: silently running an unsupported neighborhood
            # as Moore would give wrong results with no warning
            raise ValueError(
                f"unsupported neighborhood N{nb_field} in rule {spec!r} "
                f"(NM = Moore and NN = von Neumann are supported)"
            )
        return Rule(
            name=spec,
            birth=_expand_ranges(fields.get("B", "")),
            survive=_expand_ranges(fields.get("S", "")),
            radius=radius,
            states=states,
            include_center=fields.get("M", "0") == "1",
            neighborhood=neighborhood,
        )

    m = _BS_RE.match(spec) or _SB_RE.match(spec)
    if not m:
        raise ValueError(f"unrecognized rule spec {spec!r}")
    birth = frozenset(int(c) for c in m.group("b"))
    survive = frozenset(int(c) for c in m.group("s"))
    states = int(m.group("c")) if m.group("c") else 2
    return Rule(name=spec, birth=birth, survive=survive, states=states)


RULE_REGISTRY: dict[str, Rule] = {}


def register_rule(key: str, rule: Rule) -> Rule:
    RULE_REGISTRY[key] = rule
    return rule


def get_rule(name_or_spec: str) -> Rule:
    return parse_rule(name_or_spec)


# --- standard library of rules -------------------------------------------------
register_rule("conway", Rule("B3/S23", frozenset({3}), frozenset({2, 3})))
register_rule("life", RULE_REGISTRY["conway"])
register_rule("highlife", Rule("B36/S23", frozenset({3, 6}), frozenset({2, 3})))
register_rule(
    "daynight",
    Rule("B3678/S34678", frozenset({3, 6, 7, 8}), frozenset({3, 4, 6, 7, 8})),
)
register_rule("day_and_night", RULE_REGISTRY["daynight"])
register_rule("seeds", Rule("B2/S", frozenset({2}), frozenset()))
register_rule(
    "life_without_death",
    Rule("B3/S012345678", frozenset({3}), frozenset(range(9))),
)
register_rule(
    "morley", Rule("B368/S245", frozenset({3, 6, 8}), frozenset({2, 4, 5}))
)
register_rule(
    "anneal", Rule("B4678/S35678", frozenset({4, 6, 7, 8}), frozenset({3, 5, 6, 7, 8}))
)
register_rule("maze", Rule("B3/S12345", frozenset({3}), frozenset({1, 2, 3, 4, 5})))
register_rule(
    "coral", Rule("B3/S45678", frozenset({3}), frozenset({4, 5, 6, 7, 8}))
)
register_rule(
    "replicator",
    Rule("B1357/S1357", frozenset({1, 3, 5, 7}), frozenset({1, 3, 5, 7})),
)
register_rule(
    "two_by_two",
    Rule("B36/S125", frozenset({3, 6}), frozenset({1, 2, 5})),
)
register_rule("diamoeba", Rule("B35678/S5678", frozenset({3, 5, 6, 7, 8}), frozenset({5, 6, 7, 8})))
register_rule(
    "brians_brain", Rule("B2/S/C3", frozenset({2}), frozenset(), states=3)
)
register_rule(
    "star_wars",
    Rule("B2/S345/C4", frozenset({2}), frozenset({3, 4, 5}), states=4),
)
# Larger-than-Life radius-5 "Bugs" (the BASELINE.md wide-stencil config),
# in its 3-state Generations variant for the int8-multistate path.
register_rule(
    "bugs",
    Rule(
        "R5,C2,S34..58,B34..45",
        birth=_expand_ranges("34..45"),
        survive=_expand_ranges("34..58"),
        radius=5,
        states=2,
    ),
)
register_rule(
    "bugs_decay",
    Rule(
        "R5,C3,S34..58,B34..45",
        birth=_expand_ranges("34..45"),
        survive=_expand_ranges("34..58"),
        radius=5,
        states=3,
    ),
)
# Stochastic tier (tpu_life.mc, docs/STOCHASTIC.md): Metropolis Ising on
# the periodic lattice.  Temperature is per-session, not part of the rule;
# `noisy:<p>/<base>` specs are parsed, not registered (p-parameterized).
register_rule("ising", IsingRule())
# Continuous tier (tpu_life.models.lenia, docs/RULES.md): registered so
# `info` lists it; the parse path resolves the lenia: prefix before the
# registry, so this entry and parse_lenia("lenia") are the same preset.
from tpu_life.models.lenia import parse_lenia as _parse_lenia  # noqa: E402

register_rule("lenia", _parse_lenia("lenia"))
# The reference binary's *effective* rule as shipped: its unconditional rule-overwrite makes
# the B3 branch dead code, so live' = (count == 2 and live), i.e. B/S2
# (Parallel_Life_MPI.cpp:44-50; SURVEY.md §2.2).  Offered as an explicit
# bug-compat mode, never the default.
register_rule("reference_bug_compat", Rule("B/S2", frozenset(), frozenset({2})))
