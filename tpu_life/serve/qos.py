"""Multi-tenant QoS: declarative quotas, weighted fairness, shed tiers.

Before this module every ``X-API-Key`` was one anonymous token bucket
(gateway/limits.py) — tenancy gated *rate*, never *resources* or
*ordering*.  This is the missing policy layer (docs/SERVING.md "Tenant
QoS"):

- **Identity**: an API key resolves to a :class:`TenantSpec` — a named
  tenant with a service tier (``guaranteed`` / ``best_effort``), a DRR
  weight, and quota knobs.  Unknown keys collapse into the policy's
  single ``default`` tenant, so label cardinality in the registry stays
  bounded by the policy file, not by the client population; declared
  names that are themselves long secrets are hashed by
  :func:`tenant_label` into a short stable label for the same reason.
- **Quotas** (enforced by ``SimulationService.submit`` /
  ``stream_subscribe``): ``max_sessions`` bounds a tenant's concurrent
  live sessions, ``memory_fraction`` carves the tenant a slice of the
  governor's admission budget (charged per-session at the engine
  estimate over capacity), ``max_watchers`` bounds its live stream
  watcher buffers.  Every breach is the typed
  :class:`~tpu_life.serve.errors.QuotaExceeded` — HTTP 429
  ``quota_exceeded`` — rejected before anything is stored.
- **Weighted fairness**: the scheduler's admission scan orders the
  queue by deficit-round-robin over tenants
  (:meth:`QosPolicy.admission_order`) so a hog tenant flooding the
  queue cannot starve the rest of batch slots: each tenant's share of
  admissions converges to its weight, per-tenant FIFO order is
  preserved, and a policy-less scheduler keeps the exact FIFO scan.
- **Shed tiers** (gateway): under queue pressure, best-effort tenants
  are shed at ``best_effort_water`` (a fraction of the high-water mark)
  with the typed 503 ``shed_best_effort`` + Retry-After — guaranteed
  tenants only meet the classic ``overloaded`` shed at the full mark,
  so overload degrades the free tier before any paying tenant feels it.

Pure policy + arithmetic: no HTTP, no jax/numpy — importable by the
gateway, the scheduler, tests, and the surge drill alike.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

#: The two service tiers.  ``guaranteed`` tenants are shed only by the
#: classic full-fleet valves; ``best_effort`` tenants are shed first.
TIERS = ("guaranteed", "best_effort")

#: Tenant label length past which :func:`tenant_label` hashes — keeps a
#: policy that names tenants by raw API key from minting secret-bearing
#: (and unbounded-length) label values in the shared registry.
MAX_LABEL_LEN = 32

#: The reserved tenant every unknown API key resolves to.
DEFAULT_TENANT = "default"


def tenant_label(name: str) -> str:
    """The bounded registry label for a tenant name: the name itself
    when short, else ``t-<sha256[:12]>`` — stable, short, and free of
    the secret material a key-derived name could carry."""
    if len(name) <= MAX_LABEL_LEN:
        return name
    return "t-" + hashlib.sha256(name.encode()).hexdigest()[:12]


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's declared policy (all quota fields optional)."""

    name: str
    tier: str = "best_effort"
    weight: int = 1  # DRR quantum: admissions per round relative to peers
    max_sessions: int | None = None  # concurrent live sessions
    memory_fraction: float | None = None  # slice of the governor budget
    max_watchers: int | None = None  # live stream watcher buffers
    api_keys: tuple[str, ...] = ()

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(
                f"tenant {self.name!r}: tier must be one of {TIERS}, "
                f"got {self.tier!r}"
            )
        if self.weight < 1:
            raise ValueError(
                f"tenant {self.name!r}: weight must be >= 1, got {self.weight}"
            )
        if self.max_sessions is not None and self.max_sessions < 1:
            raise ValueError(
                f"tenant {self.name!r}: max_sessions must be >= 1, "
                f"got {self.max_sessions}"
            )
        if self.memory_fraction is not None and not (
            0.0 < self.memory_fraction <= 1.0
        ):
            raise ValueError(
                f"tenant {self.name!r}: memory_fraction must be in (0, 1], "
                f"got {self.memory_fraction}"
            )
        if self.max_watchers is not None and self.max_watchers < 0:
            raise ValueError(
                f"tenant {self.name!r}: max_watchers must be >= 0, "
                f"got {self.max_watchers}"
            )

    @property
    def guaranteed(self) -> bool:
        return self.tier == "guaranteed"

    @property
    def label(self) -> str:
        return tenant_label(self.name)


@dataclass
class QosPolicy:
    """The declarative per-tenant policy the whole stack consults.

    Construction is strict (typed ValueError on any malformed field) so
    a bad ``--qos`` file fails the worker at startup, never at the
    first submit.
    """

    tenants: dict[str, TenantSpec] = field(default_factory=dict)
    default: TenantSpec = field(
        default_factory=lambda: TenantSpec(name=DEFAULT_TENANT)
    )
    # best-effort tenants are shed at this fraction of the gateway's
    # high-water mark (the lower rung of the shed ladder)
    best_effort_water: float = 0.5

    def __post_init__(self):
        if not 0.0 < self.best_effort_water <= 1.0:
            raise ValueError(
                f"best_effort_water must be in (0, 1], "
                f"got {self.best_effort_water}"
            )
        self._by_key: dict[str, TenantSpec] = {}
        for spec in self.tenants.values():
            for key in spec.api_keys:
                prior = self._by_key.setdefault(key, spec)
                if prior is not spec:
                    raise ValueError(
                        f"api key {key!r} claimed by both tenant "
                        f"{prior.name!r} and {spec.name!r}"
                    )

    # -- identity ----------------------------------------------------------
    def resolve(self, api_key: str | None) -> TenantSpec:
        """The tenant an API key belongs to; unknown (or absent) keys
        collapse into the single ``default`` tenant — bounded label
        cardinality by construction."""
        if api_key is not None:
            spec = self._by_key.get(api_key)
            if spec is not None:
                return spec
        return self.default

    def spec(self, name: str) -> TenantSpec:
        if name == self.default.name:
            return self.default
        return self.tenants.get(name, self.default)

    def tenant_weight(self, name: str) -> int:
        return self.spec(name).weight

    def names(self) -> list[str]:
        out = list(self.tenants)
        if self.default.name not in self.tenants:
            out.append(self.default.name)
        return out

    # -- weighted-fair admission order -------------------------------------
    def admission_order(
        self, sessions: list, cursor: int = 0
    ) -> list:
        """Deficit-round-robin interleave of ``sessions`` by tenant.

        Pure function: per-tenant FIFO order is preserved, and each DRR
        pass grants every tenant ``weight`` admissions before wrapping —
        so when slots are scarce, admissions divide by weight instead of
        by queue share.  ``cursor`` rotates which tenant a pass starts
        at, so ties don't always break the same way.  Single-tenant (or
        empty) inputs come back unchanged.
        """
        buckets: dict[str, list] = {}
        for s in sessions:
            name = getattr(s, "tenant", None) or self.default.name
            buckets.setdefault(name, []).append(s)
        if len(buckets) <= 1:
            return list(sessions)
        names = sorted(buckets)
        start = cursor % len(names)
        names = names[start:] + names[:start]
        order: list = []
        deficit = dict.fromkeys(names, 0.0)
        remaining = sum(len(b) for b in buckets.values())
        while remaining:
            for name in names:
                bucket = buckets[name]
                if not bucket:
                    deficit[name] = 0.0  # no banking while idle
                    continue
                deficit[name] += self.tenant_weight(name)
                while bucket and deficit[name] >= 1.0:
                    order.append(bucket.pop(0))
                    deficit[name] -= 1.0
                    remaining -= 1
        return order

    # -- construction -------------------------------------------------------
    @classmethod
    def from_dict(cls, doc: dict) -> "QosPolicy":
        """Build from the declarative document shape::

            {"tenants": [{"name": ..., "tier": ..., "weight": ...,
                          "api_keys": [...], "max_sessions": ...,
                          "memory_fraction": ..., "max_watchers": ...}],
             "default": {"tier": ..., ...},
             "best_effort_water": 0.5}
        """
        if not isinstance(doc, dict):
            raise ValueError("qos policy must be a JSON object")
        unknown = sorted(set(doc) - {"tenants", "default", "best_effort_water"})
        if unknown:
            raise ValueError(
                f"qos policy: unknown top-level field(s) {', '.join(unknown)}"
            )
        tenants: dict[str, TenantSpec] = {}
        rows = doc.get("tenants", [])
        if not isinstance(rows, list):
            raise ValueError("'tenants' must be a list")
        for row in rows:
            spec = _parse_spec(row)
            if spec.name in tenants:
                raise ValueError(f"duplicate tenant {spec.name!r}")
            tenants[spec.name] = spec
        default = cls.__dataclass_fields__["default"].default_factory()
        if "default" in doc:
            row = dict(doc["default"])
            row.setdefault("name", DEFAULT_TENANT)
            row.pop("api_keys", None)  # default is the unknown-key sink
            default = _parse_spec(row)
        kwargs = {}
        if "best_effort_water" in doc:
            kwargs["best_effort_water"] = float(doc["best_effort_water"])
        return cls(tenants=tenants, default=default, **kwargs)

    @classmethod
    def load(cls, path: str) -> "QosPolicy":
        """Read a policy file (JSON).  Typed ValueError on bad shape, so
        a worker with a bad ``--qos`` file dies at startup with a
        message, never silently falls back to no policy."""
        with open(path, encoding="utf-8") as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}: not valid JSON: {e}") from None
        try:
            return cls.from_dict(doc)
        except ValueError as e:
            raise ValueError(f"{path}: {e}") from None


_SPEC_FIELDS = frozenset(
    ("name", "api_keys", "tier", "weight", "max_sessions",
     "memory_fraction", "max_watchers")
)


def _parse_spec(row) -> TenantSpec:
    if not isinstance(row, dict):
        raise ValueError(f"tenant row must be an object, got {row!r}")
    name = row.get("name")
    if not isinstance(name, str) or not name:
        raise ValueError(f"tenant row needs a non-empty 'name': {row!r}")
    unknown = sorted(set(row) - _SPEC_FIELDS)
    if unknown:
        # a typo'd field ("keys" for "api_keys") must not silently yield
        # a tenant nobody can reach — the load contract is die-loud
        raise ValueError(
            f"tenant {name!r}: unknown field(s) {', '.join(unknown)} "
            f"(known: {', '.join(sorted(_SPEC_FIELDS))})"
        )
    keys = row.get("api_keys", [])
    if not isinstance(keys, list) or not all(isinstance(k, str) for k in keys):
        raise ValueError(f"tenant {name!r}: 'api_keys' must be a string list")
    kwargs: dict = {"name": name, "api_keys": tuple(keys)}
    if "tier" in row:
        kwargs["tier"] = row["tier"]
    if "weight" in row:
        kwargs["weight"] = int(row["weight"])
    if row.get("max_sessions") is not None:
        kwargs["max_sessions"] = int(row["max_sessions"])
    if row.get("memory_fraction") is not None:
        kwargs["memory_fraction"] = float(row["memory_fraction"])
    if row.get("max_watchers") is not None:
        kwargs["max_watchers"] = int(row["max_watchers"])
    return TenantSpec(**kwargs)


__all__ = [
    "DEFAULT_TENANT",
    "MAX_LABEL_LEN",
    "QosPolicy",
    "TIERS",
    "TenantSpec",
    "tenant_label",
]
