"""Admission-time memory governance: the serve tier's byte accountant.

The realistic first OOM in a multi-tenant batched service is not a slot
— it is a **CompileKey**: every new (rule, shape, dtype, backend) mints
a fresh engine holding a ``(capacity, h, w)`` board batch, its double
buffer (the device executors retain the in-flight chunk's input batch),
and the stochastic tier's per-slot carry words.  Nothing bounded that
sum: a client fanning out varied geometries would grow device memory
until XLA raised ``RESOURCE_EXHAUSTED`` mid-round and killed the whole
worker.  This module makes the footprint a *number checked at submit*:

- :func:`estimate_engine_bytes` — the per-CompileKey estimator, pure
  arithmetic over the engine layouts (``serve.engine`` /
  ``mc.engine``): board batch x double buffer on the device executors,
  the MC key/counter/threshold carries, and the bitplane-packed lane
  layout (uint32 words of 32 spins) when the key would take the packed
  engine;
- :func:`resolve_budget` — ``ServeConfig.memory_budget_bytes`` or, when
  unset, a per-device default derived from ``utils.platform.
  device_info()`` (memoized; the probe is bounded so a wedged
  accelerator degrades the default, never hangs construction);
- :func:`check_admission` — the submit-time verdict: an existing key
  admits for free (its slots are preallocated), a new key must fit next
  to every *reserved* key (live engines plus the keys of queued
  sessions), and the failure is the typed
  :class:`~tpu_life.serve.errors.InsufficientMemory` — ``transient``
  when the key would fit alone (503 + Retry-After at the gateway),
  permanent when it can never fit (413).

The estimate is deliberately a **floor with the dominant terms only**
(boards dominate: the per-slot aux vectors are O(capacity) words).  It
exists to turn "the worker died mid-round" into "the request was
refused typed"; the in-place recovery ladder (``scheduler.
recover_engine``) catches whatever slips past the estimate.
"""

from __future__ import annotations

import os
import threading

from tpu_life.serve.errors import InsufficientMemory

#: Default budget per resolved device, by platform kind.  Deliberately
#: conservative for accelerators (the smallest deployed HBM of the
#: family) and generous-but-bounded for hosts; override with
#: ``ServeConfig.memory_budget_bytes`` (or the CLI flags) when the real
#: capacity is known.  ``<= 0`` disables accounting entirely.
GIB = 1 << 30
DEFAULT_BYTES_PER_DEVICE: dict[str, int] = {
    "tpu": 8 * GIB,
    "gpu": 8 * GIB,
    "cuda": 8 * GIB,
    "rocm": 8 * GIB,
    "cpu": 2 * GIB,
    "host": 2 * GIB,
}

#: Bound on the one-time device probe the default-budget path runs: a
#: wedged accelerator plugin must degrade the default (1 device, host
#: rate), never stall service construction toward a supervisor timeout.
BUDGET_PROBE_TIMEOUT_S = float(os.environ.get("TPU_LIFE_BUDGET_PROBE_S", 10.0))

_DEFAULT_LOCK = threading.Lock()
_DEFAULT_BUDGET: int | None = None
_DEFAULT_PER_DEVICE: int | None = None

#: Whole-board copies the mesh tier's halo-exchange scan keeps resident
#: per shard: the board itself plus its halo-extended working copy
#: (``parallel/halo.py`` pads ``radius x block_steps`` each side).
MESH_COPIES = 2


def estimate_engine_bytes(key, capacity: int, *, mc_packed: bool = True) -> int:
    """Estimated resident bytes of the engine ``key`` would mint.

    Pure arithmetic — no engine is built, no device touched — so the
    admission check costs nanoseconds.  Terms, matching the executor
    layouts in ``serve.engine`` / ``mc.engine``:

    - board batch: ``capacity x h x w`` int8, or ``capacity x h x
      packed_width(w)`` uint32 on the bitplane-packed stochastic path
      (32 spins per lane — 8x fewer bytes, the packed tier's whole
      point);
    - double buffer: the device (jax) executors retain the in-flight
      chunk's input batch (``_prev``), so the board term doubles there;
      host executors hold one copy;
    - MC carries: per-slot key halves + absolute step counter (3 x
      uint32) and the uint32[5] acceptance table, plus the shared int32
      remaining vector.
    """
    if str(getattr(key, "backend", "")).startswith("mesh:"):
        # mega-board tier (serve/mesh_engine.py): capacity is pinned to
        # 1 — the board owns its slice — so the batched ``capacity``
        # multiplier never applies, whatever the scheduler's batch size
        return estimate_mesh_bytes(key)
    h, w = key.shape
    stochastic = bool(getattr(key.rule, "stochastic", False))
    packed = False
    if stochastic and key.backend == "jax" and mc_packed:
        from tpu_life.mc import packed_supports

        packed = packed_supports(key.rule)
    if packed:
        from tpu_life.mc.packed import packed_width

        board_bytes = capacity * h * packed_width(w) * 4
    else:
        # element width from the key's dtype: 1 for int8 boards, 4 for
        # the continuous tier's float32 boards (docs/SERVING.md
        # estimator table)
        import numpy as _np

        itemsize = _np.dtype(getattr(key, "dtype", "int8")).itemsize
        board_bytes = capacity * h * w * itemsize
    copies = 2 if key.backend == "jax" else 1  # the double buffer
    total = board_bytes * copies
    total += capacity * 4  # the remaining-steps vector (int32)
    if stochastic:
        total += capacity * 4 * 3  # k0 / k1 / absolute step counter
        total += capacity * 4 * 5  # the uint32[5] acceptance table
    return total


def estimate_mesh_bytes(key) -> int:
    """Whole-slice footprint of the capacity-1 mesh engine ``key`` would
    mint (serve/mesh_engine.py): one board spread over the slice, times
    :data:`MESH_COPIES` for the halo-exchange working set, plus the
    single remaining-steps word.  The slice total is what admission
    charges against the worker budget; :func:`estimate_mesh_shard_bytes`
    breaks the same number into per-shard estimator rows."""
    import numpy as _np

    h, w = key.shape
    itemsize = _np.dtype(getattr(key, "dtype", "int8")).itemsize
    return h * w * itemsize * MESH_COPIES + 4


def estimate_mesh_shard_bytes(key, mesh_shape) -> dict[str, int]:
    """Per-shard estimator rows for a ``mesh_shape`` placement of
    ``key``: ``{"RxC-shard": bytes}`` — every shard is the same size
    (the backend pads to divisibility), a ceil-divided block plus its
    halo ring, :data:`MESH_COPIES` copies.  These are the
    ``serve_mesh_estimated_bytes{key,shard}`` gauge rows
    (docs/SERVING.md "Mega-board sessions")."""
    import numpy as _np

    h, w = key.shape
    rows, cols = int(mesh_shape[0]), int(mesh_shape[1])
    itemsize = _np.dtype(getattr(key, "dtype", "int8")).itemsize
    shard_h = -(-h // rows)
    shard_w = -(-w // cols)
    radius = max(1, int(getattr(key.rule, "radius", 1)))
    per = (shard_h * shard_w + 2 * radius * (shard_h + shard_w)) * itemsize
    per *= MESH_COPIES
    return {f"{r}x{c}": per for r in range(rows) for c in range(cols)}


def default_per_device_bytes() -> int:
    """Default memory per resolved device — the denominator of the
    mesh-eligibility hint when no slice is configured locally.  Memoized
    alongside :func:`default_budget` (same bounded probe)."""
    global _DEFAULT_PER_DEVICE
    with _DEFAULT_LOCK:
        if _DEFAULT_PER_DEVICE is None:
            from tpu_life.utils.platform import device_info

            _, kind = device_info(timeout_s=BUDGET_PROBE_TIMEOUT_S)
            _DEFAULT_PER_DEVICE = DEFAULT_BYTES_PER_DEVICE.get(
                kind, DEFAULT_BYTES_PER_DEVICE["host"]
            )
        return _DEFAULT_PER_DEVICE


def mesh_min_devices(key, per_device_bytes: int) -> int:
    """Smallest mesh slice (device count) whose per-device share holds
    ``key``'s slice total — the machine-readable "minimum slice size" a
    never-fits 413 carries so clients and the router can target a
    mesh-capable fleet instead of giving up."""
    total = estimate_mesh_bytes(key)
    per_device_bytes = max(1, int(per_device_bytes))
    return max(2, -(-total // per_device_bytes))


def mesh_hint(key, budget: int | None, mesh_devices: int = 0):
    """``(mesh_eligible, min_devices)`` for a never-fits rejection.

    Eligible means "a mesh-capable fleet can run this": the rule has a
    sharded path (deterministic or continuous — the stochastic tier has
    no sharded Monte-Carlo executor) and the key is not already a mesh
    key (a mesh slice that still overflows its budget is hopeless, not
    resubmittable).  ``min_devices`` divides the slice total by the
    per-device share — the local slice's (``budget / mesh_devices``)
    when one is configured, the platform default otherwise.
    """
    if getattr(key.rule, "stochastic", False):
        return False, None
    if str(getattr(key, "backend", "")).startswith("mesh:"):
        return False, None
    if mesh_devices and budget:
        per_device = max(1, int(budget) // int(mesh_devices))
    else:
        per_device = default_per_device_bytes()
    return True, mesh_min_devices(key, per_device)


def default_budget() -> int:
    """The derived budget: ``devices x DEFAULT_BYTES_PER_DEVICE[kind]``,
    resolved once per process through the watchdogged device probe
    (``utils.platform.device_info``) and memoized — a wedged plugin
    costs one bounded wait, then every later service construction is
    free."""
    global _DEFAULT_BUDGET
    with _DEFAULT_LOCK:
        if _DEFAULT_BUDGET is None:
            from tpu_life.utils.platform import device_info

            devices, kind = device_info(timeout_s=BUDGET_PROBE_TIMEOUT_S)
            per = DEFAULT_BYTES_PER_DEVICE.get(kind, DEFAULT_BYTES_PER_DEVICE["host"])
            _DEFAULT_BUDGET = max(1, devices) * per
        return _DEFAULT_BUDGET


def resolve_budget(configured: int | None) -> int | None:
    """``ServeConfig.memory_budget_bytes`` -> the effective budget.

    ``None`` derives the per-device default; ``<= 0`` is the explicit
    opt-out (accounting disabled, returned as None)."""
    if configured is None:
        return default_budget()
    configured = int(configured)
    return configured if configured > 0 else None


def check_admission(
    key,
    reserved: dict,
    budget: int | None,
    capacity: int,
    *,
    mc_packed: bool = True,
    mesh_devices: int = 0,
) -> None:
    """Raise :class:`InsufficientMemory` when admitting a session of
    ``key`` would overflow ``budget``.

    ``reserved`` maps every key already holding (or about to hold) an
    engine — live engines plus the distinct keys of queued sessions —
    to its estimated bytes.  A key already reserved admits for free:
    its batch is preallocated and a new session only occupies an
    existing slot.
    """
    if budget is None or key in reserved:
        return
    need = estimate_engine_bytes(key, capacity, mc_packed=mc_packed)
    if need > budget:
        eligible, min_dev = mesh_hint(key, budget, mesh_devices)
        raise InsufficientMemory(
            f"session's engine needs ~{need} bytes "
            f"(capacity {capacity}, shape {key.shape[0]}x{key.shape[1]}, "
            f"backend {key.backend}) but the memory budget is {budget} "
            f"bytes — it can never fit; shrink the board or raise "
            f"--memory-budget-bytes"
            + (
                f" (mesh-eligible: a slice of >= {min_dev} devices holds it)"
                if eligible
                else ""
            ),
            transient=False,
            estimated_bytes=need,
            budget_bytes=budget,
            mesh_eligible=eligible,
            min_devices=min_dev,
        )
    held = sum(reserved.values())
    if held + need > budget:
        raise InsufficientMemory(
            f"admitting this CompileKey needs ~{need} bytes but "
            f"{held} of the {budget}-byte budget is held by "
            f"{len(reserved)} resident key(s); retry after they drain "
            f"(or release_idle_engines)",
            transient=True,
            estimated_bytes=need,
            budget_bytes=budget,
        )


def reserved_bytes(
    engines: dict, queued_keys, capacity: int, *, mc_packed: bool = True
) -> dict:
    """The reserved-key map :func:`check_admission` consumes: every live
    engine's key plus every distinct CompileKey waiting in the queue
    (its engine will be minted at admit), each at its estimate."""
    out = {}
    for key in engines:
        out[key] = estimate_engine_bytes(key, capacity, mc_packed=mc_packed)
    for key in queued_keys:
        if key not in out:
            out[key] = estimate_engine_bytes(key, capacity, mc_packed=mc_packed)
    return out


def _reset_default_budget_for_tests() -> None:
    global _DEFAULT_BUDGET
    with _DEFAULT_LOCK:
        _DEFAULT_BUDGET = None
