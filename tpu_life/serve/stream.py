"""Live session streams: per-round delta frames behind a bounded ring.

Everything before this module is batch-shaped — submit, poll, fetch one
final board.  The interactive tier (ROADMAP item 2: watch a board evolve,
poke it, share it with other watchers) needs a *streaming result
channel*, and the serving stack already produces its raw material for
free: the pipelined pump's retire phase holds every engine's newest
MATERIALIZED board (the double buffer — ``engine.peek_slot``), so a
per-round delta costs one host subtraction, never a device sync.

The pieces:

- **Frame codec** (:func:`make_keyframe` / :func:`make_delta` /
  :func:`apply_frame`): the wire grammar of docs/STREAMING.md.  A
  keyframe carries the whole board (RLE for int rules through the
  existing ``io/rle.py`` codec; base64 float32 for the continuous
  tier), stamped with the **producing executor and a content CRC** so a
  resumed stream asserts continuity typed instead of silently mixing
  anchors (the PR 15 float-anchor limit, docs/RULES.md).  A delta
  carries a binary changed-cell mask (always the two-state ``b``/``o``
  RLE dialect) — for two-state rules the mask IS the XOR of the
  double-buffered boards; multi-state and float rules add the new
  values at the masked cells (``values_b64``).  Float deltas are
  **masked-threshold**: cells moving less than ``atol`` stay unmasked,
  and the producer diffs against its own *reconstruction* rather than
  the true board, so a client's board is always within ``atol`` of the
  truth and byte-identical to the producer's reconstruction (the delta
  CRC asserts exactly that).
- **StreamHub**: per-sid frame state behind one condition variable.
  ``produce`` appends under the hub lock (bounded ring — a slow reader
  can NEVER stall the pump; overflow drops the oldest frames and the
  reader resyncs through a typed ``frame_gap`` marker + keyframe);
  ``read`` blocks handler threads, never the pump.
- **Edit-log replay** (:func:`replay_edit_log`): the bit-reproducibility
  oracle for steered sessions — a solo run replaying the same edit log
  through the host-synchronous pump on the oracle executor, which the
  equivalence tests (and the stream chaos drill) byte-compare against
  the served session.

Frame grammar (one JSON object per frame; the wire is ndjson)::

    {"type":"key","seq":0,"step":0,"h":32,"w":32,"rle":"...",
     "executor":"jax:VmapEngine","crc":123456}          # int rules
    {"type":"key","seq":0,"step":0,"h":32,"w":32,"b64":"...",
     "dtype":"float32","executor":"numpy:HostBatchEngine","crc":...}
    {"type":"delta","seq":1,"step":16,"mask":"<rle>","crc":...}
    {"type":"delta","seq":2,"step":32,"mask":"<rle>",
     "values_b64":"...","crc":...}                       # multi-state/float
    {"type":"edit","seq":3,"step":32,"cells":[[r,c,v],...]}
    {"type":"frame_gap","seq":4,"dropped":7}
    {"type":"end","seq":5,"step":64,"state":"done"}

Sequence numbers are strictly consecutive per session — across worker
deaths too: the spill manifest carries ``stream_seq`` (frames produced
so far) and the survivor's hub fast-forwards to a reconnecting
watcher's cursor, serving a fresh keyframe there, so the client's
sequence is gapless under the same trace_id.  ``edit`` frames are
metadata (clients must NOT mutate their board on them — the next delta
already spans the edit); they exist so a watcher can mirror steering
and a postmortem can replay the log.
"""

from __future__ import annotations

import base64
import threading
import time
import zlib
from collections import deque

import numpy as np

from tpu_life.io.rle import emit_rle, parse_rle

#: Default bound on frames retained per session — past it the oldest
#: frames drop (the reader resyncs typed through ``frame_gap``).
RING_FRAMES = 64

#: Emit a fresh keyframe every this many frames even without a gap, so a
#: late subscriber (or a drifted float reconstruction) resyncs cheaply.
KEY_EVERY = 32

#: The float delta threshold: cells moving less than this stay unmasked.
#: Matches the continuous tier's equivalence tolerance
#: (``models.lenia.FLOAT_ATOL``) so reconstruction error never exceeds
#: what the executors are allowed to disagree by anyway.
FLOAT_DELTA_ATOL = 1e-4

#: Bound on cells per PATCH — an edit is a poke, not a board upload.
MAX_EDIT_CELLS = 4096


class StreamProtocolError(ValueError):
    """A frame failed to decode or apply (CRC mismatch, bad grammar)."""


# -- the frame codec --------------------------------------------------------
def board_crc(board: np.ndarray) -> int:
    """The content CRC stamped on every board-bearing frame: crc32 of
    the canonical bytes (int8 for discrete boards, little-endian float32
    for continuous ones) — what a resumed stream asserts continuity on."""
    if np.issubdtype(board.dtype, np.floating):
        buf = np.ascontiguousarray(board, dtype="<f4").tobytes()
    else:
        buf = np.ascontiguousarray(board, dtype=np.int8).tobytes()
    return zlib.crc32(buf) & 0xFFFFFFFF


def make_keyframe(
    seq: int, step: int, board: np.ndarray, *, executor: str = ""
) -> dict:
    """A full-board frame: the resync anchor every delta chain hangs off.

    Stamped with the producing ``executor`` and the content CRC
    (docs/RULES.md "float anchors"): float frames are allclose-not-byte
    across executors, so a client splicing streams from two workers
    checks both stamps and resyncs from this keyframe instead of
    applying a foreign delta chain to a drifted board.
    """
    h, w = board.shape
    frame = {
        "type": "key",
        "seq": int(seq),
        "step": int(step),
        "h": int(h),
        "w": int(w),
        "executor": executor,
        "crc": board_crc(board),
    }
    if np.issubdtype(board.dtype, np.floating):
        frame["b64"] = base64.b64encode(
            np.ascontiguousarray(board, dtype="<f4").tobytes()
        ).decode("ascii")
        frame["dtype"] = "float32"
    else:
        states = max(2, int(board.max(initial=0)) + 1)
        frame["rle"] = emit_rle(board, states=states)
    return frame


def make_delta(
    seq: int,
    step: int,
    prev: np.ndarray,
    new: np.ndarray,
    *,
    atol: float = FLOAT_DELTA_ATOL,
) -> tuple[dict, np.ndarray]:
    """One per-round delta frame plus the reconstruction it produces.

    Returns ``(frame, recon)`` — the caller must keep ``recon`` (not
    ``new``) as the next diff base: for float boards the two differ (the
    masked-threshold cut), and diffing against the reconstruction is
    what bounds a client's drift at ``atol`` forever instead of letting
    sub-threshold residue accumulate.  For int boards ``recon is new``.

    The mask is ALWAYS the two-state ``b``/``o`` RLE dialect (a binary
    changed-cell grid fits it whatever the rule's state count).  For
    two-state int rules the mask alone reconstructs (flip the masked
    cells — it IS the XOR of the double-buffered boards); multi-state
    int and float rules carry the new values at the masked cells in
    row-major order (``values_b64``: int8, or little-endian float32).
    """
    frame: dict = {"type": "delta", "seq": int(seq), "step": int(step)}
    if np.issubdtype(new.dtype, np.floating):
        mask = np.abs(new.astype(np.float32) - prev.astype(np.float32)) > atol
        recon = np.array(prev, dtype=np.float32, copy=True)
        recon[mask] = np.asarray(new, dtype=np.float32)[mask]
        if mask.any():
            frame["values_b64"] = base64.b64encode(
                np.ascontiguousarray(recon[mask], dtype="<f4").tobytes()
            ).decode("ascii")
    else:
        mask = np.asarray(new) != np.asarray(prev)
        recon = np.ascontiguousarray(new, dtype=np.int8)
        two_state = (
            int(recon.max(initial=0)) <= 1
            and int(np.asarray(prev).max(initial=0)) <= 1
        )
        if mask.any() and not two_state:
            frame["values_b64"] = base64.b64encode(
                recon[mask].astype(np.int8).tobytes()
            ).decode("ascii")
    frame["mask"] = emit_rle(mask.astype(np.int8), states=2)
    frame["crc"] = board_crc(recon)
    return frame, recon


def apply_frame(board: np.ndarray | None, frame: dict) -> np.ndarray | None:
    """Client-side application: fold one frame into the running board.

    Returns the new board (``None`` after a ``frame_gap`` — the delta
    chain is broken; the caller waits for the next keyframe).  ``edit``
    and ``end`` frames are metadata and return ``board`` unchanged.
    Raises :class:`StreamProtocolError` on CRC mismatch, a delta with no
    base, or unparseable grammar — the typed signal to resync.
    """
    kind = frame.get("type")
    if kind == "key":
        h, w = int(frame["h"]), int(frame["w"])
        if "b64" in frame:
            buf = base64.b64decode(frame["b64"])
            new = np.frombuffer(buf, dtype="<f4")
            if new.size != h * w:
                raise StreamProtocolError(
                    f"keyframe b64 holds {new.size} cells, expected {h * w}"
                )
            new = new.reshape(h, w).astype(np.float32)
        else:
            new, _ = parse_rle(frame["rle"])
            if new.shape != (h, w):
                # RLE headers are authoritative but defensive: a torn
                # frame must fail typed, not reshape into junk
                raise StreamProtocolError(
                    f"keyframe RLE decoded to {new.shape}, expected {(h, w)}"
                )
        if board_crc(new) != frame.get("crc"):
            raise StreamProtocolError(
                f"keyframe seq {frame.get('seq')} CRC mismatch"
            )
        return new
    if kind == "delta":
        if board is None:
            raise StreamProtocolError(
                f"delta seq {frame.get('seq')} with no keyframe base"
            )
        mask_board, _ = parse_rle(frame["mask"])
        mask = np.zeros(board.shape, dtype=bool)
        mh, mw = mask_board.shape
        mask[:mh, :mw] = mask_board.astype(bool)
        n = int(mask.sum())
        new = np.array(board, copy=True)
        if "values_b64" in frame:
            buf = base64.b64decode(frame["values_b64"])
            if np.issubdtype(board.dtype, np.floating):
                vals = np.frombuffer(buf, dtype="<f4")
            else:
                vals = np.frombuffer(buf, dtype=np.int8)
            if vals.size != n:
                raise StreamProtocolError(
                    f"delta seq {frame.get('seq')} carries {vals.size} "
                    f"values for a {n}-cell mask"
                )
            new[mask] = vals
        elif n:
            # two-state flip: the mask IS the XOR
            new[mask] = 1 - new[mask]
        if board_crc(new) != frame.get("crc"):
            raise StreamProtocolError(
                f"delta seq {frame.get('seq')} CRC mismatch "
                f"(splice across executors? resync from a keyframe)"
            )
        return new
    if kind == "frame_gap":
        return None
    if kind in ("edit", "end", "shed", "stream_error"):
        return board
    raise StreamProtocolError(f"unknown frame type {kind!r}")


# -- edits ------------------------------------------------------------------
def validate_cells(cells, shape: tuple[int, int], rule) -> list:
    """A PATCH body's cell list -> canonical ``[(r, c, v), ...]``.

    Typed ``ValueError`` (the gateway's 400) on anything malformed:
    out-of-range coordinates, out-of-range states, floats on a discrete
    rule, NaN on the continuous tier, or an oversized mask.
    """
    if not isinstance(cells, list) or not cells:
        raise ValueError("'cells' must be a non-empty list of [row, col, value]")
    if len(cells) > MAX_EDIT_CELLS:
        raise ValueError(
            f"edit has {len(cells)} cells; the limit is {MAX_EDIT_CELLS}"
        )
    h, w = shape
    continuous = bool(getattr(rule, "continuous", False))
    states = rule.states
    out = []
    for i, cell in enumerate(cells):
        if not isinstance(cell, (list, tuple)) or len(cell) != 3:
            raise ValueError(f"cells[{i}] must be [row, col, value]")
        r, c, v = cell
        if isinstance(r, bool) or isinstance(c, bool) or not isinstance(r, int) or not isinstance(c, int):
            raise ValueError(f"cells[{i}] coordinates must be integers")
        if not (0 <= r < h and 0 <= c < w):
            raise ValueError(
                f"cells[{i}] = ({r}, {c}) is outside the {h}x{w} board"
            )
        if continuous:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"cells[{i}] value must be a number")
            v = float(v)
            if not np.isfinite(v) or not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"cells[{i}] value {v} must be a finite number in [0, 1]"
                )
        else:
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(
                    f"cells[{i}] value must be an integer state for rule "
                    f"{rule.name!r}"
                )
            if not 0 <= v < states:
                raise ValueError(
                    f"cells[{i}] value {v} is outside this rule's states "
                    f"0..{states - 1}"
                )
        out.append((int(r), int(c), v))
    return out


def apply_cells(board: np.ndarray, cells) -> None:
    """Write an edit's cells into ``board`` in place (already validated)."""
    for r, c, v in cells:
        board[r, c] = v


def parse_edit_log(raw) -> list:
    """A wire/manifest edit log -> canonical ``[(step, [(r,c,v),...]),...]``
    sorted by step.  Shape-validated only (values are re-validated
    against the rule at submit via :func:`validate_cells`)."""
    if not isinstance(raw, list):
        raise ValueError("'edits' must be a list of [step, cells] entries")
    out = []
    for i, entry in enumerate(raw):
        if not isinstance(entry, (list, tuple)) or len(entry) != 2:
            raise ValueError(f"edits[{i}] must be [step, cells]")
        step, cells = entry
        if isinstance(step, bool) or not isinstance(step, int) or step < 0:
            raise ValueError(f"edits[{i}] step must be an integer >= 0")
        out.append((int(step), cells))
    out.sort(key=lambda e: e[0])
    return out


def render_edit_log(edits) -> list:
    """Canonical edit log -> the JSON shape the manifest and resume wire
    carry: ``[[step, [[r, c, v], ...]], ...]``."""
    return [
        [int(step), [[r, c, v] for (r, c, v) in cells]]
        for step, cells in edits
    ]


def estimate_stream_bytes(
    shape: tuple[int, int], dtype: str, ring_frames: int = RING_FRAMES
) -> int:
    """Estimated resident bytes one session's delta ring can grow to —
    what the governor charges at subscribe (docs/SERVING.md "Resource
    governance").  Dominant terms: the reconstruction base board, one
    resident keyframe, and the ring's deltas (bounded by a conservative
    1/8 of board size each plus framing overhead)."""
    h, w = shape
    itemsize = np.dtype(dtype).itemsize
    board_bytes = h * w * itemsize
    return 2 * board_bytes + ring_frames * (board_bytes // 8 + 512)


# -- the hub ----------------------------------------------------------------
class _SessionStream:
    """One session's frame state: ring + cursors, owned by the hub lock."""

    __slots__ = (
        "frames",
        "base_seq",
        "next_seq",
        "last_board",
        "last_step",
        "need_key",
        "frames_since_key",
        "done",
        "watchers",
    )

    def __init__(self, start_seq: int = 0):
        self.frames: deque = deque()
        self.base_seq = int(start_seq)  # seq of frames[0]
        self.next_seq = int(start_seq)
        self.last_board: np.ndarray | None = None  # the reconstruction base
        self.last_step = -1
        self.need_key = True
        self.frames_since_key = 0
        self.done = False
        self.watchers = 0


class StreamHub:
    """Per-session delta rings between the pump and the watcher sockets.

    The pump (under the service lock) calls :meth:`produce` /
    :meth:`record_edit` / :meth:`finish` — bounded appends under the
    hub's own lock, so a slow or dead reader can never stall a round.
    Handler threads block in :meth:`read` on the hub condition; the hub
    never holds the service lock, the service never blocks on a socket.
    """

    def __init__(
        self,
        *,
        ring_frames: int = RING_FRAMES,
        key_every: int = KEY_EVERY,
        atol: float = FLOAT_DELTA_ATOL,
    ):
        if ring_frames < 2:
            raise ValueError(f"ring_frames must be >= 2, got {ring_frames}")
        self.ring_frames = ring_frames
        self.key_every = key_every
        self.atol = atol
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._streams: dict[str, _SessionStream] = {}
        # cumulative totals the service mirrors into its registry
        self.frames_total = 0
        self.gaps_total = 0

    # -- pump side (service lock held by the caller; hub lock here) --------
    def active(self) -> bool:
        """Cheap pump-side gate: any session has stream state at all."""
        return bool(self._streams)

    def wants(self, sid: str) -> bool:
        """Does this session need frames produced?  True while stream
        state exists and no terminal frame has been emitted — frames are
        produced lazily, only for sessions somebody subscribed to."""
        st = self._streams.get(sid)
        return st is not None and not st.done

    def ensure(self, sid: str, start_seq: int = 0) -> None:
        with self._cond:
            if sid not in self._streams:
                self._streams[sid] = _SessionStream(start_seq)

    def subscribe(self, sid: str, start_seq: int = 0) -> None:
        with self._cond:
            st = self._streams.get(sid)
            if st is None:
                st = self._streams[sid] = _SessionStream(start_seq)
            st.watchers += 1

    def unsubscribe(self, sid: str) -> bool:
        """Drop one watcher; True when the last one left and the ring
        state was discarded (frames are produced for watchers, not for
        archival — a later subscriber restarts from a fresh keyframe,
        and its cursor fast-forwards the sequence space to stay gapless).
        """
        with self._cond:
            st = self._streams.get(sid)
            if st is None:
                return True
            st.watchers = max(0, st.watchers - 1)
            if st.watchers == 0:
                del self._streams[sid]
                self._cond.notify_all()
                return True
            return False

    def watcher_count(self) -> int:
        with self._lock:
            return sum(st.watchers for st in self._streams.values())

    def produce(
        self, sid: str, board: np.ndarray, step: int, *, executor: str = ""
    ) -> dict | None:
        """Append one frame for ``sid`` if the board progressed.

        Called from the pump's locked retire tail with the newest
        materialized board (``engine.peek_slot`` — the double buffer, so
        this never waits on the in-flight chunk).  Emits a keyframe on
        first contact / after a gap / every ``key_every`` frames, a
        delta otherwise; a repeat step (lag did not advance) is a no-op.
        """
        with self._cond:
            st = self._streams.get(sid)
            if st is None or st.done:
                return None
            if step <= st.last_step and not st.need_key:
                return None
            if st.need_key or st.last_board is None or (
                self.key_every and st.frames_since_key >= self.key_every
            ):
                frame = make_keyframe(
                    st.next_seq, step, board, executor=executor
                )
                st.last_board = np.array(board, copy=True)
                st.need_key = False
                st.frames_since_key = 0
            else:
                frame, recon = make_delta(
                    st.next_seq, step, st.last_board, board, atol=self.atol
                )
                st.last_board = recon
                st.frames_since_key += 1
            st.last_step = int(step)
            self._append(st, frame)
            return frame

    def record_edit(self, sid: str, step: int, cells) -> None:
        """The in-band steering marker: metadata only (the next delta
        already spans the edit's effect — see the module docstring)."""
        with self._cond:
            st = self._streams.get(sid)
            if st is None or st.done:
                return
            frame = {
                "type": "edit",
                "seq": st.next_seq,
                "step": int(step),
                "cells": [[r, c, v] for (r, c, v) in cells],
            }
            self._append(st, frame)

    def finish(self, sid: str, state: str, step: int) -> None:
        """The terminal frame: every watcher's read drains to EOF."""
        with self._cond:
            st = self._streams.get(sid)
            if st is None or st.done:
                return
            frame = {
                "type": "end",
                "seq": st.next_seq,
                "step": int(step),
                "state": state,
            }
            self._append(st, frame)
            st.done = True

    def discard(self, sid: str) -> None:
        with self._cond:
            self._streams.pop(sid, None)
            self._cond.notify_all()

    def seq_snapshot(self, sid: str, default: int = 0) -> int:
        """Frames produced so far — what the spill manifest persists as
        ``stream_seq`` so a survivor continues the sequence space."""
        with self._lock:
            st = self._streams.get(sid)
            return st.next_seq if st is not None else default

    def _append(self, st: _SessionStream, frame: dict) -> None:
        # hub lock held.  Bounded ring: overflow drops the oldest frame
        # — the pump never blocks — and schedules a keyframe so readers
        # that fell past the ring start resync typed (frame_gap + key).
        st.frames.append(frame)
        st.next_seq += 1
        self.frames_total += 1
        while len(st.frames) > self.ring_frames:
            st.frames.popleft()
            st.base_seq += 1
            st.need_key = True
            self.gaps_total += 1
        self._cond.notify_all()

    # -- reader side (handler threads; only the hub lock) ------------------
    def read(
        self, sid: str, cursor: int, timeout: float | None = 0.25
    ) -> tuple[list, int, bool]:
        """Frames from ``cursor`` on: ``(frames, next_cursor, eof)``.

        Blocks up to ``timeout`` for new frames.  A cursor that fell
        behind the ring start gets one typed ``frame_gap`` marker and
        resumes at the next keyframe in the ring (one is always coming:
        overflow schedules it).  A cursor AHEAD of the sequence space —
        a watcher reconnecting across a failover with frames the dead
        worker produced but this one has not — fast-forwards the hub:
        the survivor's next frame is a keyframe at exactly that cursor,
        which is what keeps reconnected sequence numbers gapless.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                st = self._streams.get(sid)
                if st is None:
                    return [], cursor, True
                if cursor > st.next_seq and not st.done:
                    # failover fast-forward (see docstring).  Any frames
                    # this incarnation produced below the cursor are
                    # cleared so the ring invariant (frames[i].seq ==
                    # base_seq + i) holds for the jumped space; a
                    # concurrent slower reader resyncs typed (frame_gap
                    # + the keyframe this schedules).
                    st.frames.clear()
                    st.base_seq = cursor
                    st.next_seq = cursor
                    st.need_key = True
                out = self._collect(st, cursor)
                if out is not None:
                    frames, next_cursor = out
                    if frames or (st.done and next_cursor >= st.next_seq):
                        return frames, next_cursor, st.done and next_cursor >= st.next_seq
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return [], cursor, False
                    self._cond.wait(remaining)
                else:
                    self._cond.wait()

    def _collect(self, st: _SessionStream, cursor: int):
        """Hub lock held: the deliverable frames at ``cursor``, or None
        when the reader must keep waiting (behind the ring with no
        keyframe landed yet)."""
        if cursor >= st.base_seq:
            idx = cursor - st.base_seq
            frames = list(st.frames)[idx:] if idx < len(st.frames) else []
            return frames, cursor + len(frames)
        # behind the ring: resync at the first keyframe currently held
        for i, frame in enumerate(st.frames):
            if frame.get("type") == "key":
                seq = st.base_seq + i
                gap = {
                    "type": "frame_gap",
                    "seq": cursor,
                    "dropped": seq - cursor,
                }
                frames = [gap] + list(st.frames)[i:]
                return frames, st.base_seq + len(st.frames)
        if st.done:
            # never resyncable: everything from here out is undeliverable
            gap = {
                "type": "frame_gap",
                "seq": cursor,
                "dropped": st.next_seq - cursor,
            }
            return [gap], st.next_seq
        return None


# -- the replay oracle ------------------------------------------------------
def replay_edit_log(
    board: np.ndarray,
    rule,
    steps: int,
    edits,
    *,
    seed: int | None = None,
    temperature: float | None = None,
    start_step: int = 0,
    backend: str = "numpy",
    chunk_steps: int = 16,
) -> np.ndarray:
    """The steering bit-reproducibility oracle (docs/STREAMING.md).

    Runs ONE session through the host-synchronous pump on ``backend``
    (default: the numpy ground-truth executor), re-applying ``edits`` —
    canonical ``[(step, cells), ...]`` in ABSOLUTE step space — at
    exactly their recorded steps, and returns the final board.  The
    contract the tests and the stream chaos drill assert: a served
    session's bytes equal this replay's bytes (allclose at
    ``models.lenia.FLOAT_ATOL`` for the continuous tier), however many
    watchers, edits, pump shapes, or worker deaths the original saw.
    """
    from tpu_life.serve.service import ServeConfig, SimulationService

    svc = SimulationService(
        ServeConfig(
            capacity=1,
            chunk_steps=chunk_steps,
            backend=backend,
            pipeline=False,
            memory_budget_bytes=0,
        )
    )
    try:
        sid = svc.submit(
            board,
            rule,
            steps,
            seed=seed,
            temperature=temperature,
            start_step=start_step,
            scheduled_edits=edits,
        )
        svc.drain(max_rounds=10 * (steps + chunk_steps + len(list(edits)) * 2) + 16)
        return svc.result(sid)
    finally:
        svc.close()
