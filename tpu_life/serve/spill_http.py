"""The remote spill store: the checkpoint contract ported to the wire.

Durability today rests on a local directory (``serve.spill``), which
quietly assumes the rescuer shares a filesystem with the victim.  This
module breaks that assumption with three pieces, keeping the SAME
crash-consistency contract — atomic publish, CRC32 witness, newest-2
retention, demote-to-predecessor on a failed intact check:

- :class:`SpillHTTPServer` — a small stdlib HTTP object store any worker
  or supervisor can host (``tpu-life spill-store``).  Objects live under
  ``<root>/<namespace>/<sid>/``; every PUT carries an ``X-CRC32`` header
  the server verifies against the received body BEFORE publishing (a
  torn upload can never be published as truth), and publishes atomically
  (tmp + rename) next to a CRC sidecar it replays on GET.
- :class:`HttpSpillBackend` — the worker-side
  :class:`~tpu_life.serve.spill.SpillBackend`: per-operation timeouts,
  bounded jittered retry on REFUSALS only (connection refused, typed
  503 — the request was definitively not applied; a timeout or
  mid-exchange reset is never blindly re-sent even though PUTs are
  idempotent, matching the fleet's no-ambiguous-retry discipline), and
  any exhausted/ambiguous failure surfaces as :class:`OSError` so the
  service's existing graceful degradation (that session ->
  ``spill_disabled``, the pump never stalls) is what runs.
- :func:`read_remote_sessions` — the migration tier's read path: same
  triage as ``read_spill_sessions`` (corrupt / disabled / demote), with
  the CRC check re-run on the DOWNLOADED bytes, so a body torn on the
  wire demotes to the predecessor snapshot exactly like disk rot.

The failure matrix (docs/FLEET.md "Cross-host topology"):

====================  =======================================
fault                 outcome
====================  =======================================
connect refused       bounded jittered retry, then OSError
typed 503             bounded jittered retry, then OSError
timeout               OSError (write) / demote (read)
reset mid-body        OSError (write) / demote (read)
torn / short body     400 at the server (write) / demote (read)
CRC mismatch on read  demote to predecessor, else corrupt sid
other 4xx/5xx         OSError (write) / corrupt (read)
====================  =======================================

On the write side every OSError degrades ONE session to
``spill_disabled``; on the read side "corrupt" is the typed 410
``spill_corrupt`` and a missing namespace is simply zero records
(``never_snapshotted`` for its sids).
"""

from __future__ import annotations

import http.client
import json
import re
import shutil
import socket
import threading
import time
import urllib.error
import urllib.request
import zlib
from http.server import ThreadingHTTPServer
from pathlib import Path

import numpy as np

from tpu_life import chaos
from tpu_life.gateway.errors import ApiError, backoff_delay, parse_retry_after
from tpu_life.io.codec import decode_board, encode_board
from tpu_life.runtime.checkpoint import atomic_publish
from tpu_life.runtime.metrics import log
from tpu_life.serve.spill import (
    DISABLED,
    KEEP_SNAPSHOTS,
    MANIFEST,
    SpillBackend,
    SpillRecord,
)

#: URL prefix of the store API.
ROUTE_SPILL = "/v1/spill"

#: Namespace / sid / object names: one path segment, no traversal.  The
#: dots admit ``manifest.json`` / ``DISABLED.json``; ``..`` is refused.
_SAFE = re.compile(r"(?!\.\.?$)[A-Za-z0-9][A-Za-z0-9._-]*$")

_SNAP = re.compile(r"snap_(\d{9})$")


def snap_name(step: int) -> str:
    return f"snap_{int(step):09d}"


def _require_safe(*names: str) -> None:
    for n in names:
        if not _SAFE.match(n):
            raise ApiError(400, "bad_name", f"illegal path segment {n!r}")


# ---------------------------------------------------------------------------
# the server: a CRC-checked, atomically-published object store
# ---------------------------------------------------------------------------
class SpillHTTPServer:
    """Host a spill namespace tree over HTTP (stdlib only — the store is
    plumbing, and any fleet process can carry it)."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        # import here, not at module top: gateway.server is where the
        # shared JSON envelope plumbing lives
        from tpu_life.gateway.server import JsonHandler

        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        outer = self

        class _Handler(JsonHandler):
            server_version = "tpu-life-spill/1"
            log_tag = "spill-store"

            def do_GET(self):  # noqa: N802
                outer._dispatch(self, "GET")

            def do_PUT(self):  # noqa: N802
                outer._dispatch(self, "PUT")

            def do_DELETE(self):  # noqa: N802
                outer._dispatch(self, "DELETE")

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._server.allow_reuse_address = True
        self.host, self.port = self._server.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="spill-store",
            daemon=True,
        )
        self._thread.start()
        log.info("spill-store: serving %s at %s", self.root, self.url)

    def close(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, h, method: str) -> None:
        try:
            self._route(h, method, h.path.rstrip("/"))
        except ApiError as e:
            try:
                h._send_json(e.status, e.body(), retry_after=e.retry_after)
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception:
            log.exception("spill-store: %s %s failed", method, h.path)
            try:
                h._send_json(
                    500,
                    {"error": {"code": "internal", "message": "internal error"}},
                )
            except (BrokenPipeError, ConnectionResetError):
                pass

    def _route(self, h, method: str, path: str) -> None:
        if path == "/healthz":
            h._send_json(200, {"status": "ok"})
            return
        if path == ROUTE_SPILL and method == "GET":
            # namespace listing — the control plane's orphan sweep
            spaces = sorted(
                p.name for p in self.root.iterdir() if p.is_dir()
            ) if self.root.is_dir() else []
            h._send_json(200, {"namespaces": spaces})
            return
        if not path.startswith(ROUTE_SPILL + "/"):
            raise ApiError(404, "not_found", f"no route for {path}")
        parts = path[len(ROUTE_SPILL) + 1 :].split("/")
        _require_safe(*parts)
        if len(parts) == 1:
            ns = self.root / parts[0]
            if method == "GET":
                h._send_json(200, self._listing(ns))
            elif method == "DELETE":
                shutil.rmtree(ns, ignore_errors=True)
                h._send_json(200, {"deleted": parts[0]})
            else:
                raise ApiError(405, "method_not_allowed", method)
            return
        if len(parts) == 2:
            d = self.root / parts[0] / parts[1]
            if method != "DELETE":
                raise ApiError(405, "method_not_allowed", method)
            shutil.rmtree(d, ignore_errors=True)
            h._send_json(200, {"deleted": f"{parts[0]}/{parts[1]}"})
            return
        if len(parts) != 3:
            raise ApiError(404, "not_found", path)
        obj = self.root / parts[0] / parts[1] / parts[2]
        if method == "PUT":
            self._put(h, obj)
        elif method == "GET":
            self._get(h, obj)
        elif method == "DELETE":
            obj.unlink(missing_ok=True)
            _crc_file(obj).unlink(missing_ok=True)
            h._send_json(200, {"deleted": parts[2]})
        else:
            raise ApiError(405, "method_not_allowed", method)

    def _listing(self, ns: Path) -> dict:
        """Per-sid snapshot steps + marker flags — everything the read
        path needs to triage without N round-trips per object."""
        sids: dict[str, dict] = {}
        if ns.is_dir():
            for d in sorted(p for p in ns.iterdir() if p.is_dir()):
                snaps = sorted(
                    int(m.group(1))
                    for f in d.iterdir()
                    if (m := _SNAP.match(f.name))
                )
                sids[d.name] = {
                    "snaps": snaps,
                    "manifest": (d / MANIFEST).exists(),
                    "disabled": (d / DISABLED).exists(),
                }
        return {"namespace": ns.name, "sids": sids}

    def _put(self, h, obj: Path) -> None:
        body = h._read_sized_body(64 * 1024 * 1024)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        claimed = h.headers.get("X-CRC32")
        try:
            intact = claimed is not None and int(claimed) == crc
        except ValueError:
            intact = False  # a garbled witness is a torn upload, typed
        if not intact:
            # a torn/garbled upload: refuse BEFORE publishing — the store
            # must never hold bytes that disagree with their witness
            raise ApiError(
                400,
                "crc_mismatch",
                f"body crc32 {crc} != claimed {claimed!r}; upload torn?",
            )
        try:
            obj.parent.mkdir(parents=True, exist_ok=True)
            with atomic_publish(obj) as tmp:
                tmp.write_bytes(body)
            with atomic_publish(_crc_file(obj)) as tmp:
                tmp.write_text(str(crc))
        except (FileNotFoundError, FileExistsError):
            # a concurrent DELETE of the sid/namespace swept the dir out
            # from under the write (mark_disabled and the migrator's reap
            # both rmtree): the publish loses its tmp (ENOENT), or mkdir's
            # exist_ok re-check races the rmtree (EEXIST then not-a-dir).
            # The store no longer wants these bytes; typed, so the writer
            # degrades without a server stack trace
            raise ApiError(
                409, "deleted_concurrently", f"{obj.parent} was deleted mid-write"
            ) from None
        h._send_json(200, {"stored": obj.name, "crc32": crc})

    def _get(self, h, obj: Path) -> None:
        try:
            body = obj.read_bytes()
        except OSError:
            raise ApiError(404, "not_found", f"no object {obj.name}") from None
        try:
            crc = int(_crc_file(obj).read_text())
        except (OSError, ValueError):
            crc = zlib.crc32(body) & 0xFFFFFFFF
        h.send_response(200)
        h.send_header("Content-Type", "application/octet-stream")
        h.send_header("Content-Length", str(len(body)))
        h.send_header("X-CRC32", str(crc))
        h.end_headers()
        h.wfile.write(body)


def _crc_file(obj: Path) -> Path:
    return obj.with_name(obj.name + ".crc32")


# ---------------------------------------------------------------------------
# the worker-side backend
# ---------------------------------------------------------------------------
class HttpSpillBackend(SpillBackend):
    """Spill through a remote :class:`SpillHTTPServer`.

    Every operation is bounded by ``timeout_s``; refusals (connection
    refused, typed 503) retry up to ``retries`` times on the shared
    jittered-exponential curve; anything else — timeout, reset, 4xx/5xx —
    raises :class:`OSError`, which the service's spill pass translates
    into that one session's ``spill_disabled`` degradation.  All writes
    run in the pump's unlocked settle window, so a slow or dead store
    costs durability, never the service.

    ``namespace`` is this worker incarnation's slice of the store; a
    wire-registered worker rebinds it when the control plane grants a
    fresh ``(worker, generation)`` (:meth:`set_namespace`).
    """

    def __init__(
        self,
        base_url: str,
        namespace: str,
        *,
        timeout_s: float = 5.0,
        retries: int = 3,
        backoff_s: float = 0.1,
        max_backoff_s: float = 2.0,
        jitter: float = 0.25,
        rng=None,
        sleep=time.sleep,
    ):
        self.base_url = base_url.rstrip("/")
        if not _SAFE.match(namespace):
            raise ValueError(f"illegal spill namespace {namespace!r}")
        self.timeout_s = timeout_s
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.rng = rng
        self.sleep = sleep
        self._lock = threading.Lock()
        self._namespace = namespace
        self._written: dict[str, list[int]] = {}
        self._edit_counts: dict[str, int] = {}

    @property
    def namespace(self) -> str:
        with self._lock:
            return self._namespace

    def set_namespace(self, namespace: str) -> None:
        """Rebind to a fresh incarnation namespace (a wire-registered
        worker whose lease was re-granted under a new generation).  The
        write-tracking resets with it: the new namespace holds nothing,
        and the OLD one is the migrator's to read and reap — never ours
        to keep appending to."""
        if not _SAFE.match(namespace):
            raise ValueError(f"illegal spill namespace {namespace!r}")
        with self._lock:
            if namespace == self._namespace:
                return
            self._namespace = namespace
            self._written = {}
            self._edit_counts = {}
        log.info("spill: rebound to remote namespace %s", namespace)

    # -- transport ----------------------------------------------------------
    def _url(self, sid: str, obj: str | None = None, *, ns: str | None = None) -> str:
        # multi-request operations (save: snapshot PUT + manifest PUT +
        # prunes) must pass the SAME captured ``ns`` to every request — a
        # concurrent set_namespace (Registrar re-grant) between reads
        # would otherwise split one spill across two incarnations
        tail = f"/{obj}" if obj else ""
        return f"{self.base_url}{ROUTE_SPILL}/{ns or self.namespace}/{sid}{tail}"

    def _request(
        self,
        method: str,
        url: str,
        body: bytes | None = None,
        *,
        retry: bool = True,
    ) -> tuple[int, dict, bytes]:
        """One store operation -> (status, headers, body).  Chaos seams
        and the refusal-only retry loop live here; exhausted retries and
        every ambiguous transport failure raise OSError."""
        attempt = 0
        while True:
            hinted = None
            try:
                if chaos.decide("spill.remote.timeout") is not None:
                    chaos.record_fire("spill.remote.timeout", "timeout")
                    raise socket.timeout(
                        "chaos: injected remote-spill timeout"
                    )
                if chaos.partitioned("spill", self.base_url):
                    raise ConnectionRefusedError(
                        "chaos: net partition to spill store"
                    )
                req = urllib.request.Request(url, data=body, method=method)
                if body is not None:
                    req.add_header("Content-Type", "application/octet-stream")
                    req.add_header(
                        "X-CRC32", str(zlib.crc32(body) & 0xFFFFFFFF)
                    )
                with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                    return r.status, dict(r.headers), r.read()
            except urllib.error.HTTPError as e:
                if e.code == 503 and retry and attempt < self.retries:
                    # a typed refusal: nothing was applied — pace, retry.
                    # The store's explicit Retry-After wins un-jittered
                    # over the backoff curve (the shared doctrine); drain
                    # the error body so the connection isn't left
                    # half-read behind the retry
                    hinted = parse_retry_after(e.headers)
                    try:
                        e.read()
                    except (OSError, http.client.HTTPException):
                        pass
                else:
                    try:
                        return e.code, dict(e.headers), e.read()
                    except (OSError, http.client.HTTPException) as e2:
                        raise OSError(
                            f"spill store {method} {url}: error body torn: {e2}"
                        ) from None
            except http.client.HTTPException as e:
                # reset mid-body (IncompleteRead and kin): the bytes are
                # torn and the request's fate is ambiguous — never
                # re-sent, surfaced as the OSError the degradation path
                # catches (the docstring's "reset mid-body" row)
                raise OSError(f"spill store {method} {url}: {e}") from None
            except (urllib.error.URLError, ConnectionError, socket.timeout, TimeoutError) as e:
                reason = getattr(e, "reason", e)
                refused = isinstance(reason, ConnectionRefusedError) or isinstance(
                    e, ConnectionRefusedError
                )
                if not (refused and retry and attempt < self.retries):
                    # ambiguous (timeout, mid-exchange reset) or retries
                    # exhausted: surface as the OSError the degradation
                    # path catches
                    raise OSError(f"spill store {method} {url}: {e}") from None
            attempt += 1
            self.sleep(
                hinted
                if hinted is not None
                else backoff_delay(
                    attempt,
                    base=self.backoff_s,
                    cap=self.max_backoff_s,
                    jitter=self.jitter,
                    rng=self.rng,
                )
            )

    def _put(self, sid: str, obj: str, body: bytes, *, ns: str | None = None) -> None:
        status, _, raw = self._request("PUT", self._url(sid, obj, ns=ns), body)
        if status != 200:
            raise OSError(
                f"spill store refused PUT {ns or self.namespace}/{sid}/{obj}: "
                f"{status} {raw[:200]!r}"
            )

    # -- the SpillBackend contract ------------------------------------------
    def save(
        self,
        sid: str,
        board: np.ndarray,
        step: int,
        *,
        rule: str,
        steps_total: int,
        seed: int | None,
        temperature: float | None,
        timeout_s: float | None,
        trace_id: str | None = None,
        edits: list | None = None,
        scheduled_edits: list | None = None,
        stream_seq: int = 0,
    ) -> bool:
        edit_count = len(edits or []) + len(scheduled_edits or [])
        with self._lock:
            ns = self._namespace
            written = self._written.setdefault(sid, [])
            last_edits = self._edit_counts.get(sid, 0)
        # a same-step save with a GROWN edit log still writes (the
        # queued-edit case — the manifest changed, the step did not)
        if written and written[-1] == step and last_edits == edit_count:
            return False
        payload = encode_board(board)
        self._put(sid, snap_name(step), payload, ns=ns)
        manifest = {
            "sid": sid,
            "rule": rule,
            "steps_total": int(steps_total),
            "seed": seed,
            "temperature": temperature,
            "timeout_s": timeout_s,
            "trace_id": trace_id,
            "height": int(board.shape[0]),
            "width": int(board.shape[1]),
        }
        # steered-session keys only when set (byte-stable otherwise)
        if edits:
            manifest["edits"] = edits
        if scheduled_edits:
            manifest["scheduled_edits"] = scheduled_edits
        if stream_seq:
            manifest["stream_seq"] = int(stream_seq)
        self._put(sid, MANIFEST, json.dumps(manifest).encode(), ns=ns)
        with self._lock:
            self._edit_counts[sid] = edit_count
        if not written or written[-1] != step:
            written.append(step)
        # retention mirrors the local store (newest KEEP_SNAPSHOTS);
        # a failed prune is a leak, not a durability loss — best-effort
        while len(written) > KEEP_SNAPSHOTS:
            stale = written.pop(0)
            try:
                self._request(
                    "DELETE", self._url(sid, snap_name(stale), ns=ns), retry=False
                )
            except OSError:
                log.debug("spill: prune of %s step %d failed", sid, stale)
        return True

    def mark_disabled(self, sid: str) -> None:
        with self._lock:
            ns = self._namespace
            self._written.pop(sid, None)
            self._edit_counts.pop(sid, None)
        try:
            # drop the stale snapshots first (bytes we can no longer keep
            # fresh must not masquerade as a recovery point), then publish
            # the marker — both against the ONE captured namespace (a
            # Registrar re-grant between the two requests must not split
            # the disable across incarnations); on a store this
            # unreachable both may fail, which degrades the post-death
            # reason to never_snapshotted — still a truthful 410
            self._request("DELETE", self._url(sid, ns=ns), retry=False)
            body = json.dumps({"sid": sid, "reason": "spill_error"}).encode()
            self._put(sid, DISABLED, body, ns=ns)
        except OSError:
            log.warning("spill: could not publish remote disabled marker for %s", sid)

    def delete(self, sid: str) -> None:
        with self._lock:
            known = self._written.pop(sid, None) is not None
            self._edit_counts.pop(sid, None)
        if not known:
            return
        try:
            self._request("DELETE", self._url(sid), retry=False)
        except OSError:
            log.warning("spill: could not delete remote spill of %s", sid)

    def spilled_count(self) -> int:
        with self._lock:
            return len(self._written)

    def spilled_sids(self) -> list[str]:
        with self._lock:
            return list(self._written)


# ---------------------------------------------------------------------------
# the migration tier's read path
# ---------------------------------------------------------------------------
def _fetch(url: str, timeout_s: float) -> tuple[int, dict, bytes]:
    req = urllib.request.Request(url)
    try:
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, dict(r.headers), r.read()
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), e.read()
    except http.client.HTTPException as e:
        # a body torn mid-read is an OSError to callers: the snapshot
        # fetch demotes, the listing read surfaces as a migration retry
        raise OSError(f"mid-exchange failure fetching {url}: {e}") from None


def read_remote_sessions(
    base_url: str, namespace: str, *, timeout_s: float = 10.0
) -> tuple[list[SpillRecord], list[str], list[str]]:
    """Read every resumable session in a dead worker's remote namespace —
    the wire twin of ``read_spill_sessions`` with identical triage:
    ``(records, corrupt_sids, disabled_sids)``, demoting a snapshot whose
    downloaded bytes fail the CRC/shape check to its predecessor.  A
    listing failure raises OSError (the migration run records nothing and
    leaves the bytes for a retry — never deletes what nobody decoded)."""
    base = base_url.rstrip("/")
    status, _, raw = _fetch(f"{base}{ROUTE_SPILL}/{namespace}", timeout_s)
    if status != 200:
        raise OSError(f"spill store listing {namespace}: {status}")
    try:
        listing = json.loads(raw)["sids"]
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        raise OSError(f"spill store listing {namespace} unreadable: {e}") from None
    records: list[SpillRecord] = []
    corrupt: list[str] = []
    disabled: list[str] = []
    for sid in sorted(listing):
        info = listing[sid] or {}
        if info.get("disabled"):
            disabled.append(sid)
            continue
        try:
            st, _, mraw = _fetch(
                f"{base}{ROUTE_SPILL}/{namespace}/{sid}/{MANIFEST}", timeout_s
            )
            if st != 200:
                raise ValueError(f"manifest {st}")
            meta = json.loads(mraw)
            height = int(meta["height"])
            width = int(meta["width"])
            steps_total = int(meta["steps_total"])
            rule = str(meta["rule"])
        except (OSError, ValueError, KeyError, TypeError):
            log.warning("spill: remote %s/%s has no readable manifest", namespace, sid)
            corrupt.append(sid)
            continue
        chosen = None
        for step in sorted((int(s) for s in info.get("snaps", [])), reverse=True):
            board = _fetch_snapshot(
                f"{base}{ROUTE_SPILL}/{namespace}/{sid}/{snap_name(step)}",
                height,
                width,
                timeout_s,
            )
            if board is not None:
                chosen = (step, board)
                break
            log.warning(
                "spill: remote %s/%s snap %d failed the intact check; demoting",
                namespace,
                sid,
                step,
            )
        if chosen is None:
            corrupt.append(sid)
            continue
        step, board = chosen
        seed = meta.get("seed")
        temperature = meta.get("temperature")
        t_s = meta.get("timeout_s")
        trace_id = meta.get("trace_id")
        records.append(
            SpillRecord(
                sid=sid,
                rule=rule,
                board=board,
                step=step,
                steps_total=steps_total,
                seed=None if seed is None else int(seed),
                temperature=None if temperature is None else float(temperature),
                timeout_s=None if t_s is None else float(t_s),
                height=height,
                width=width,
                trace_id=None if trace_id is None else str(trace_id),
                edits=meta.get("edits"),
                scheduled_edits=meta.get("scheduled_edits"),
                stream_seq=int(meta.get("stream_seq", 0)),
            )
        )
    return records, corrupt, disabled


def _fetch_snapshot(
    url: str, height: int, width: int, timeout_s: float
) -> np.ndarray | None:
    """Download + verify one snapshot; None on ANY shortfall (HTTP error,
    torn body, CRC mismatch, bad decode) — the caller demotes."""
    try:
        status, headers, body = _fetch(url, timeout_s)
    except OSError:
        return None
    if status != 200:
        return None
    d = chaos.decide("spill.remote.torn_body")
    if d is not None:
        chaos.record_fire("spill.remote.torn_body", d.fault.mode)
        body = body[: max(1, len(body) // 2)]
    claimed = headers.get("X-CRC32")
    try:
        if claimed is None or int(claimed) != (zlib.crc32(body) & 0xFFFFFFFF):
            return None
    except ValueError:
        return None  # a garbled witness is a shortfall, not an abort
    try:
        return decode_board(body, height, width)
    except (ValueError, TypeError):
        return None


def delete_remote_namespace(
    base_url: str, namespace: str, *, timeout_s: float = 10.0
) -> None:
    """Best-effort post-rescue reap of a dead incarnation's namespace."""
    base = base_url.rstrip("/")
    try:
        req = urllib.request.Request(
            f"{base}{ROUTE_SPILL}/{namespace}", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=timeout_s):
            pass
    except (urllib.error.URLError, ConnectionError, socket.timeout, TimeoutError, OSError):
        log.warning("spill: could not reap remote namespace %s", namespace)


def list_remote_namespaces(
    base_url: str, *, timeout_s: float = 10.0
) -> list[str]:
    """All namespaces in the store (the control plane's orphan sweep)."""
    base = base_url.rstrip("/")
    status, _, raw = _fetch(f"{base}{ROUTE_SPILL}", timeout_s)
    if status != 200:
        raise OSError(f"spill store namespace listing: {status}")
    try:
        return list(json.loads(raw)["namespaces"])
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        raise OSError(f"spill store namespace listing unreadable: {e}") from None
