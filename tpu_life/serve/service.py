"""The public serving API: ``submit / poll / cancel / drain``.

:class:`SimulationService` is the in-process serving core — the piece of
the repo whose shape is an inference stack rather than a batch job.  A
network front-end would be a thin shell over exactly these four verbs;
the CLI's ``serve`` / ``submit`` modes are the first such shell.

Execution is cooperative: ``pump()`` runs one scheduling round,
``drain()`` pumps until idle.  Cooperative beats background threads here
for the same reason the driver is a synchronous loop: every test and
every caller sees a deterministic interleaving, and the host-sync chunk
boundary is already the natural scheduling quantum (sessions join and
leave the batch only there).

``pump()`` comes in two shapes (``ServeConfig.pipeline``):

- **pipelined** (the default): a double-buffered round in three phases —
  a locked *begin* (deadline expiry, admission, one async chunk dispatch
  per engine in rotated key order), an **unlocked** *settle* (device
  chunks and host-engine compute finish while submit/poll/cancel stay
  serviceable), and a locked *end* (retire the previous dispatches'
  finishers from the engines' double buffers, refill the freed slots,
  late-dispatch engines that sat out the begin).  The device rounds
  back-to-back: retirement and admission overlap the in-flight chunk
  instead of idling it.  Bit-identity with the synchronous pump (and
  with solo ``driver.run``) is structural — a finished slot is frozen by
  the in-scan mask, so *when* the host reads it cannot change *what* it
  reads — and the equivalence suites assert it.
- **sync** (``pipeline=False``): the classic host-synchronous round
  (admit -> step -> retire under one lock hold) — the oracle shape, and
  the baseline leg of ``bench.py --serve-pipeline``.

The verbs are thread-safe: one internal lock serializes ``submit`` /
``poll`` / ``result`` / ``cancel`` / ``stats`` against the pump's locked
phases, so a network front-end (``tpu_life.gateway``) can run ONE
background pump thread that owns all device work while handler threads
call the verbs concurrently — the engine's one-compile-per-CompileKey
invariant never meets a second pumping thread, and under the pipelined
pump a verb is never blocked behind device compute (a separate pump
mutex keeps a second pumping thread out of the phase machine without
making it wait on device work either).  ``begin_drain()`` is the
shutdown hook: it closes admission (``submit`` raises :class:`Draining`)
while in-flight sessions keep stepping to completion; the pipelined
drain retires every in-flight chunk before ``idle()`` reports true.

Observability rides the unified obs layer (docs/OBSERVABILITY.md): the
service generates one ``run_id``, every pump emits a ``MetricsRecorder``
record (queue depth, batch occupancy, sessions/sec, live queue-wait /
completion-latency quantiles), a labeled registry tracks the counters and
histograms behind those quantiles (exported to the JSONL sink at close
and to ``--prom-file`` as a Prometheus snapshot), ``--trace-events``
brackets every scheduling round with admit / step-chunk / retire spans
plus per-session async queue-wait intervals, and ``drain`` still runs
under ``runtime.profiling.maybe_profile`` so a device trace lands in the
same tooling as a batch run.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from tpu_life import chaos, obs
from tpu_life.models.rules import Rule, get_rule
from tpu_life.runtime import recovery
from tpu_life.runtime.checkpoint import atomic_publish as ckpt_atomic_publish
from tpu_life.runtime.metrics import MetricsRecorder, log
from tpu_life.runtime.profiling import maybe_profile
from tpu_life.serve.engine import CompileKey, compile_key_for
from tpu_life.serve.errors import (
    Draining,
    InsufficientMemory,
    QueueFull,
    QuotaExceeded,
)
from tpu_life.serve.qos import QosPolicy, tenant_label
from tpu_life.serve.scheduler import RoundStats, Scheduler
from tpu_life.serve.stream import (
    StreamHub,
    estimate_stream_bytes,
    parse_edit_log,
    render_edit_log,
    validate_cells,
)
from tpu_life.serve.sessions import (
    SessionState,
    SessionStore,
    SessionView,
    TERMINAL,
)


@dataclass
class ServeConfig:
    capacity: int = 8  # batch slots per compile key
    chunk_steps: int = 16  # device steps per scheduling round
    max_queue: int = 64  # bounded admission queue (backpressure)
    backend: str = "jax"  # engine executor: jax | numpy | sharded | pallas | ...
    # the pipelined (double-buffered) pump; False = the host-synchronous
    # round, kept as the bit-identity oracle and the bench baseline
    pipeline: bool = True
    default_timeout_s: float | None = None  # per-request deadline default
    metrics: bool = False  # record per-pump serve metrics
    metrics_file: str | None = None  # JSONL sink (implies metrics)
    profile: str | None = None  # jax.profiler trace dir for drain()
    # Chrome trace-event JSON (Perfetto): round spans + per-session
    # queue-wait intervals, correlated with metrics records via run_id
    trace_events: str | None = None
    prom_file: str | None = None  # Prometheus text snapshot, written at close
    run_id: str | None = None  # correlation id (generated when unset)
    # durable sessions (docs/SERVING.md "durability"): when set, every
    # ``spill_every`` rounds each live session's board + manifest is
    # spilled to <spill_dir>/<sid>/ through the crash-consistent
    # checkpoint contract, so a supervisor can resume a SIGKILLed
    # worker's sessions on a survivor (docs/FLEET.md failover).  The
    # spill write runs off the pipelined pump's unlocked settle window —
    # it never blocks submit/poll/cancel.
    spill_dir: str | None = None
    spill_every: int = 4  # rounds between spill passes
    # the remote spill backend (docs/FLEET.md "Cross-host topology"):
    # instead of a local directory, spill through an HTTP spill store
    # (``tpu-life spill-store``) under ``spill_namespace`` — the same
    # atomic-publish + CRC contract on the wire, so a migrator on
    # ANOTHER machine can read the rescue.  Mutually exclusive with
    # ``spill_dir`` (typed error at construction).
    spill_url: str | None = None
    spill_namespace: str | None = None  # default: this service's run_id
    # replicated local spill (docs/FLEET.md): > 1 fans every spill write
    # through N replica sub-stores under spill_dir (reads-any with
    # demotion on the rescue path); 1 = the plain single store
    spill_replicas: int = 1
    # the stochastic tier's bitplane knob (docs/STOCHASTIC.md packed
    # tier): ising batches run on the bitplane-packed device engine (32
    # spins per uint32 lane, bit-identical to the roll path).  False
    # (--no-bitpack) pins the int8 roll engines — the oracle
    # configuration the packed path is byte-compared against in CI.
    mc_packed: bool = True
    # the neighborhood-counting path (--stencil, docs/RULES.md): "roll"
    # shift-adds, "matmul" banded matmuls (bit-identical for integer
    # rules, the MXU path for large radii and the continuous tier), or
    # "auto" — the measured crossover model per rule, with the numpy
    # executor pinned to roll so the oracle never silently moves.
    # Resolved per CompileKey at submit (ops.conv.resolve_stencil).
    stencil: str = "auto"
    # the resource governor (docs/SERVING.md "Resource governance"):
    # admission-time memory budget for the estimated engine footprint.
    # None derives devices x per-kind default from device_info(); <= 0
    # disables accounting.  A submit whose CompileKey would overflow it
    # raises the typed InsufficientMemory instead of letting XLA OOM
    # kill the worker mid-round.
    memory_budget_bytes: int | None = None
    # in-place recovery budget per CompileKey: chunk-level RECOVERABLE
    # faults are masked by rebuild-and-replay (OOM takes the halve-chunk
    # -> host-demotion ladder) this many times before falling back to
    # the typed per-key failure.  0 = pure failure isolation (PR 10).
    engine_max_restarts: int = 3
    # the wedge watchdog: a pipelined settle window still blocked after
    # this many seconds marks the service WEDGED — finishers of already-
    # settled engines are salvaged and /readyz flips to 500 with a
    # machine-readable reason, so a fleet supervisor's unready-recycle +
    # migration path rescues the sessions.  None disables the watchdog.
    settle_deadline_s: float | None = None
    # time-series retention (docs/OBSERVABILITY.md "Time series"): the
    # pump's retire tail snapshots the registry into a bounded ring at
    # most once per series_every_s, scraped non-destructively through
    # GET /v1/debug/series?cursor=.  0 disables the ring entirely — the
    # hot path then pays one is-None check and nothing else.
    series_every_s: float = 1.0
    series_max_snapshots: int = 512
    # tenant QoS (docs/SERVING.md "Tenant QoS"): the declarative
    # per-tenant policy — identity, quotas, DRR weights, shed tiers.
    # None (the default) keeps the whole stack tenant-blind: no quota
    # checks, FIFO admission, zero per-tenant label cardinality.
    qos: QosPolicy | None = None
    # the mega-board mesh tier (docs/SERVING.md "Mega-board sessions"):
    # the device count of the slice reserved for sessions whose governor
    # verdict is "never fits on one chip".  0 disables the tier — those
    # sessions stay a typed 413 (now carrying the mesh_eligible hint).
    # When > 0, a never-fits deterministic/continuous session is
    # converted at submit into a ``mesh:RxC`` CompileKey (shape from
    # ``serve.mesh_engine.plan_mesh_shape``) and runs capacity-1 on the
    # sharded halo-exchange backend, coexisting with batched small
    # sessions on the remaining capacity.
    mesh_devices: int = 0


class SimulationService:
    def __init__(self, config: ServeConfig | None = None, *, clock=time.monotonic):
        self.config = config or ServeConfig()
        if self.config.max_queue < 1:
            # a zero-length queue can never admit anything: every submit
            # would bounce and a retry-on-QueueFull client would spin
            raise ValueError(
                f"max_queue must be >= 1, got {self.config.max_queue}"
            )
        # fail at construction, not at the first admission's lazy engine
        # build (EngineBase re-checks, but by then sessions are queued)
        if self.config.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.config.capacity}")
        if self.config.chunk_steps < 1:
            raise ValueError(
                f"chunk_steps must be >= 1, got {self.config.chunk_steps}"
            )
        if (
            self.config.spill_dir is not None
            or self.config.spill_url is not None
        ) and self.config.spill_every < 1:
            raise ValueError(
                f"spill_every must be >= 1, got {self.config.spill_every}"
            )
        if self.config.engine_max_restarts < 0:
            raise ValueError(
                f"engine_max_restarts must be >= 0, "
                f"got {self.config.engine_max_restarts}"
            )
        if (
            self.config.settle_deadline_s is not None
            and self.config.settle_deadline_s <= 0
        ):
            raise ValueError(
                f"settle_deadline_s must be > 0, "
                f"got {self.config.settle_deadline_s}"
            )
        if self.config.series_every_s < 0:
            raise ValueError(
                f"series_every_s must be >= 0 (0 disables sampling), "
                f"got {self.config.series_every_s}"
            )
        if self.config.series_every_s > 0 and self.config.series_max_snapshots < 1:
            raise ValueError(
                f"series_max_snapshots must be >= 1, "
                f"got {self.config.series_max_snapshots}"
            )
        if self.config.mesh_devices < 0:
            raise ValueError(
                f"mesh_devices must be >= 0 (0 disables the mesh tier), "
                f"got {self.config.mesh_devices}"
            )
        from tpu_life.ops.conv import validate_stencil

        validate_stencil(self.config.stencil)
        self.clock = clock
        self.run_id = self.config.run_id or obs.new_run_id()
        self.store = SessionStore()
        self.scheduler = Scheduler(
            capacity=self.config.capacity,
            chunk_steps=self.config.chunk_steps,
            max_queue=self.config.max_queue,
            mc_packed=self.config.mc_packed,
            qos=self.config.qos,
            engine_max_restarts=self.config.engine_max_restarts,
            clock=clock,
            observer=self,
        )
        # the resource governor (docs/SERVING.md "Resource governance"):
        # the effective budget is resolved ONCE — the derived default
        # runs a bounded device probe, memoized process-wide — so submit
        # pays pure arithmetic
        from tpu_life.serve import governor

        self._governor = governor
        self._memory_budget = governor.resolve_budget(
            self.config.memory_budget_bytes
        )
        self.registry = obs.MetricsRegistry()
        self.recorder = MetricsRecorder(
            0,
            self.config.metrics,
            sink=self.config.metrics_file,
            run_id=self.run_id,
            registry=self.registry,
        )
        # the serve instrument set (docs/OBSERVABILITY.md): queue pressure,
        # batch health, admission outcomes, and the two latency
        # distributions a multi-tenant service is judged by
        self._g_queue_depth = self.registry.gauge(
            "serve_queue_depth", "sessions waiting for a batch slot"
        )
        # head-of-line demand (docs/OBSERVABILITY.md "Time series"):
        # depth says how many wait, age says how badly we're behind —
        # the pair the autoscaler's input contract needs
        self._g_queue_age = self.registry.gauge(
            "serve_queue_age_oldest_seconds",
            "wall age of the oldest still-queued session",
        )
        self._g_occupancy = self.registry.gauge(
            "serve_batch_occupancy", "occupied slot fraction at the last step"
        )
        self._c_submitted = self.registry.counter(
            "serve_sessions_submitted_total", "sessions accepted by submit()"
        )
        self._c_rejections = self.registry.counter(
            "serve_admission_rejections_total",
            "submissions bounced by backpressure (queue full, or transient "
            "memory pressure from the governor)",
        )
        # liveness for file scrapers: a stalled pump shows as a frozen
        # round counter even while every gauge legitimately sits still
        self._c_rounds = self.registry.counter(
            "serve_rounds_total", "scheduling rounds executed"
        )
        # step throughput as registry counters (not just the per-round
        # record's plain ints): the sampled time series and `tpu-life
        # top` derive steps/s and the packed fraction from these
        self._c_steps = self.registry.counter(
            "serve_steps_total", "device steps advanced across all sessions"
        )
        self._c_steps_packed = self.registry.counter(
            "serve_packed_steps_total",
            "the slice of serve_steps_total run by bitplane-packed engines",
        )
        self._c_finished = self.registry.counter(
            "serve_sessions_finished_total",
            "sessions reaching a terminal state, by outcome",
            labels=("state",),
        )
        self._h_queue_wait = self.registry.histogram(
            "serve_queue_wait_seconds", "submit-to-admission wait"
        )
        self._h_latency = self.registry.histogram(
            "serve_completion_seconds", "submit-to-terminal-state latency"
        )
        # the overlap instruments (ISSUE 7): how many chunks are in flight
        # after dispatch (0 = host-synchronous), and how long engines sat
        # with nothing in flight between a collect and the next dispatch —
        # the seconds the pipelined pump exists to reclaim
        self._g_pipeline_depth = self.registry.gauge(
            "serve_pipeline_depth",
            "device chunks in flight after the round's dispatch phase",
        )
        self._c_device_idle = self.registry.counter(
            "serve_device_idle_seconds_total",
            "wall seconds engines had no chunk in flight between dispatches",
        )
        # the durability instruments (docs/SERVING.md): how long each
        # spill pass takes (the failover overhead being paid) and how many
        # sessions currently have a resumable spill on disk
        self._h_snapshot = self.registry.histogram(
            "serve_snapshot_seconds", "wall seconds per session-spill pass"
        )
        self._g_spilled = self.registry.gauge(
            "serve_spilled_sessions", "live sessions with a spill on disk"
        )
        # disk-full graceful degradation (docs/CHAOS.md): spill writes
        # that failed (ENOSPC, dead disk).  Each failure disables spill
        # for THAT session only — it keeps running without durability —
        # and the pump survives; the counter is the operator's signal
        self._c_spill_errors = self.registry.counter(
            "serve_spill_errors_total",
            "failed session-spill writes (the session degrades to "
            "spill-disabled; the service keeps serving)",
        )
        # engine compile counts by CompileKey bucket (rule:HxW:backend —
        # a closed set in any sane deployment; the cap bounds the rest)
        self._g_compiles = self.registry.gauge(
            "serve_engine_compile_count",
            "compiled batch programs per engine",
            labels=("compile_key",),
        )
        # the resource-governor instruments (docs/SERVING.md "Resource
        # governance"): the admission budget, the per-key estimated
        # engine footprint it is charged against, every typed admission
        # rejection by reason, and every in-place engine recovery by
        # ladder outcome
        self._g_mem_budget = self.registry.gauge(
            "serve_memory_budget_bytes",
            "admission-time memory budget for estimated engine footprints "
            "(0 = accounting disabled)",
        )
        self._g_est_bytes = self.registry.gauge(
            "serve_estimated_bytes",
            "estimated resident bytes per live engine",
            labels=("key",),
        )
        self._c_adm_rejected = self.registry.counter(
            "serve_admission_rejected_total",
            "typed admission rejections by reason",
            labels=("reason",),
        )
        self._c_recoveries = self.registry.counter(
            "serve_engine_recoveries_total",
            "in-place engine recoveries by outcome (replayed / "
            "oom_halved_chunk / oom_host_demoted / budget_exhausted / "
            "rebuild_failed / wedged)",
            labels=("outcome",),
        )
        # the stencil-path gauge (docs/RULES.md / OBSERVABILITY.md): how
        # many live CompileKeys compiled the banded-matmul counting path
        # — merged across the fleet by `tpu-life stats` like the packed
        # attribution was
        self._g_matmul_keys = self.registry.gauge(
            "serve_matmul_keys",
            "live engines whose CompileKey compiled the matmul stencil",
        )
        self._g_matmul_keys.labels()
        # the mega-board mesh tier (docs/SERVING.md "Mega-board
        # sessions"): how many live sessions run sharded over a mesh
        # slice, and the governor's per-shard estimator rows — one gauge
        # sample per (key bucket, shard) so an operator sees exactly
        # what each device of the slice is charged with
        self._g_mesh_sessions = self.registry.gauge(
            "serve_mesh_sessions",
            "live sessions sharded over the reserved mesh slice",
        )
        self._g_mesh_sessions.labels()
        self._g_mesh_est_bytes = self.registry.gauge(
            "serve_mesh_estimated_bytes",
            "estimated resident bytes per mesh shard of a live mega-board "
            "engine",
            labels=("key", "shard"),
        )
        # (key bucket, shard) pairs last set (zeroed when the engine goes)
        self._mesh_est_buckets: set[tuple[str, str]] = set()
        # tenant QoS observability (docs/SERVING.md "Tenant QoS"): live
        # sessions per tenant, and every typed per-tenant shed / quota
        # rejection by reason.  Label cardinality is bounded by the
        # policy (unknown keys collapse into one default tenant; long
        # names hash through tenant_label), and a policy-less service
        # never mints a single series.
        self._qos = self.config.qos
        self._g_tenant_sessions = self.registry.gauge(
            "serve_tenant_sessions",
            "live sessions per tenant",
            labels=("tenant",),
        )
        self._c_tenant_shed = self.registry.counter(
            "tenant_shed_total",
            "typed per-tenant sheds and quota rejections by reason "
            "(quota_sessions / quota_bytes / quota_watchers / "
            "shed_best_effort)",
            labels=("tenant", "reason"),
        )
        # tenant label buckets last set (stale buckets zero out, the
        # _est_buckets discipline)
        self._tenant_buckets: set[str] = set()
        # the span-ring loss counter (docs/OBSERVABILITY.md "Distributed
        # tracing"): events evicted from the bounded trace buffer between
        # scrapes — a nonzero value tells the doctor a journey may have
        # holes that are collection loss, not anomalies
        self._c_trace_dropped = self.registry.counter(
            "trace_spans_dropped_total",
            "trace events evicted from the bounded span ring before any "
            "scrape or write could collect them",
        )
        self._c_trace_dropped.labels()
        self._trace_dropped_seen = 0
        # the live-stream tier (docs/STREAMING.md): per-session delta
        # rings between the pump's retire phase and the watcher sockets.
        # The hub has its OWN lock — the pump appends bounded frames
        # under it, handler threads block in read() on it, and neither
        # ever holds the service lock across a socket
        self.hub = StreamHub()
        # governor charge per streamed sid (docs/SERVING.md "Resource
        # governance"): the first watcher of a session reserves its delta
        # ring's estimated bytes against the admission budget
        self._stream_charged: dict[str, int] = {}
        self._g_stream_watchers = self.registry.gauge(
            "stream_watchers", "live stream subscriptions on this worker"
        )
        self._c_stream_frames = self.registry.counter(
            "stream_frames_total", "delta-stream frames produced"
        )
        self._c_stream_gaps = self.registry.counter(
            "stream_frame_gaps_total",
            "frames dropped from bounded delta rings (slow readers resync "
            "through a typed frame_gap marker; the pump never stalls)",
        )
        for fam in (
            self._g_stream_watchers,
            self._c_stream_frames,
            self._c_stream_gaps,
        ):
            fam.labels()
        # mirror floors: the hub's plain-int totals folded into the
        # registry as monotone deltas each round (the trace_dropped
        # pattern)
        self._stream_frames_seen = 0
        self._stream_gaps_seen = 0
        self._g_mem_budget.set(float(self._memory_budget or 0))
        # key buckets whose estimated-bytes gauge was last set (released
        # engines' buckets zero out in the next round's sweep)
        self._est_buckets: set[str] = set()
        # prime the unlabeled series so a snapshot taken before the first
        # event still shows them (a zero rejection counter is information;
        # an absent one is a question)
        for fam in (
            self._g_queue_depth,
            self._g_queue_age,
            self._g_occupancy,
            self._c_submitted,
            self._c_rejections,
            self._c_rounds,
            self._c_steps,
            self._c_steps_packed,
            self._h_queue_wait,
            self._h_latency,
            self._g_pipeline_depth,
            self._c_device_idle,
            self._h_snapshot,
            self._g_spilled,
            self._c_spill_errors,
        ):
            fam.labels()
        # chaos observability (docs/CHAOS.md): injections fired in this
        # process land in the shared registry — /metrics, the prom file,
        # the JSONL snapshot.  A disarmed process just never ticks it.
        chaos.bind_registry(self.registry)
        # the spill backend (durable sessions): created eagerly so a bad
        # spill path — or a spill_dir/spill_url conflict — fails at
        # construction, not at the first spill pass.  The seam is
        # serve.spill.SpillBackend: local directory by default, the
        # remote HTTP store when spill_url is set (cross-host failover)
        if self.config.spill_dir is not None or self.config.spill_url is not None:
            from tpu_life.serve.spill import SpillBackend, make_spill_backend

            self._spill: SpillBackend | None = make_spill_backend(
                spill_dir=self.config.spill_dir,
                spill_url=self.config.spill_url,
                namespace=self.config.spill_namespace or self.run_id,
                replicas=self.config.spill_replicas,
            )
        else:
            self._spill = None
        self._rounds_since_spill = 0
        # count of admitted spill-urgent sessions (spill-on-adopt) that
        # may still be awaiting their first write: lets off-cadence
        # rounds skip the full slot walk in the steady state.  May
        # overcount (self-healing: any urgent-pending walk recomputes
        # it); never undercounts while a session is still urgent.
        self._spill_urgent_pending = 0
        self._snapshot_s_total = 0.0
        # the service OWNS its tracer rather than claiming the process-
        # global slot: emissions are routed through obs.activate() per
        # round, so a concurrently traced driver.run (or second service)
        # in the same process cannot steal this service's events — every
        # span lands in the file carrying its own run_id.  With no tracer
        # of our own, activate() is a no-op and emissions join whatever
        # ambient tracer is active (an untraced service inside a traced
        # driver contributes to the driver's timeline).
        self._tracer = (
            obs.Tracer(self.config.trace_events, run_id=self.run_id)
            if self.config.trace_events
            else None
        )
        self._t0 = clock()
        # time-series retention (docs/OBSERVABILITY.md "Time series"):
        # None when disabled, so the retire tail's only cost is one
        # attribute check — the tracer's one-global-check discipline,
        # pinned by the sample_count() probe in the overhead guard
        self._series = (
            obs.timeseries.SeriesRing(self.config.series_max_snapshots)
            if self.config.series_every_s > 0
            else None
        )
        self._series_next = 0.0  # monotonic deadline of the next sample
        self._completed = 0
        self._rounds = 0
        self._occupancy_sum = 0.0  # for mean batch occupancy in stats()
        # cumulative step attribution by storage path (obs): total steps
        # advanced, and the slice run by bitplane-packed engines
        self._steps_total = 0
        self._steps_packed_total = 0
        # the thread-safe seam: every verb and the pump serialize on this
        # (reentrant: cancel/pump call observer hooks while holding it)
        self._lock = threading.RLock()
        # pump exclusivity for the pipelined path: the round spans an
        # unlocked settle window, so a second pumping thread must queue at
        # the round boundary, never interleave phases
        self._pump_mutex = threading.Lock()
        self._draining = False
        # the wedge watchdog (docs/SERVING.md "Resource governance"): a
        # settle window that blocks past settle_deadline_s is the hang
        # mode recovery cannot catch in-process (nothing raises).  The
        # watchdog thread detects it FROM OUTSIDE the pump: the pump
        # publishes (start time, plan, settled-so-far) around every
        # unlocked settle window; on deadline the watchdog — under the
        # service lock, which the stuck pump does NOT hold — marks the
        # service wedged, salvages the already-settled engines' pending
        # finishers, and /readyz answers 500 with the machine-readable
        # reason so a supervisor's unready-recycle + migration rescues
        # the rest.  Sticky by design: a declared wedge means the
        # deadline contract was broken; the recycle path owns recovery.
        self._wedged: dict | None = None
        self._settle_state: tuple | None = None
        self._watchdog_stop = threading.Event()
        self._watchdog: threading.Thread | None = None
        if self.config.settle_deadline_s is not None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serve-watchdog", daemon=True
            )
            self._watchdog.start()

    # -- the four verbs ----------------------------------------------------
    def submit(
        self,
        board: np.ndarray,
        rule: Rule | str,
        steps: int,
        *,
        timeout_s: float | None = None,
        fault_at: int = 0,
        seed: int | None = None,
        temperature: float | None = None,
        start_step: int = 0,
        trace_id: str | None = None,
        edits=None,
        scheduled_edits=None,
        stream_seq: int = 0,
        mesh_resume_dir: str | None = None,
        tenant: str | None = None,
    ) -> str:
        """Admit one simulation request; returns its session id.

        ``tenant`` is the resolved tenant name (docs/SERVING.md "Tenant
        QoS") the gateway derived from ``X-API-Key`` through the
        :class:`~tpu_life.serve.qos.QosPolicy`.  With a configured
        policy the tenant's declared quotas are enforced here — typed
        :class:`QuotaExceeded` BEFORE anything is stored, the QueueFull
        discipline — and the admission scan orders the queue by
        deficit-round-robin over tenants.  None (the library default)
        admits tenant-blind, exactly as before.

        ``mesh_resume_dir`` is the shard-wise mega-board resume pointer
        (docs/SERVING.md "Mega-board sessions"): a spilled tile-set
        directory on a shared filesystem.  ``board`` is then only a
        geometry-carrying placeholder — the session re-gathers tile by
        tile at admission through ``MeshEngine.load_tiles`` (possibly
        onto a different mesh shape than the one that spilled), so the
        full board is never materialized on this host.  Requires a
        configured mesh slice (``mesh_devices >= 2``).

        ``edits`` / ``scheduled_edits`` / ``stream_seq`` are the steered-
        session resume fields (docs/STREAMING.md): ``edits`` is a prior
        life's APPLIED edit log (``[[step, [[r, c, v], ...]], ...]``,
        every step <= start_step — already baked into ``board``, carried
        for provenance), ``scheduled_edits`` its not-yet-applied tail
        (start_step <= step < start_step + total steps — re-applied at
        exactly the recorded steps during re-execution, which is what
        extends the bit-reproducibility contract to edited sessions),
        and ``stream_seq`` the frames a prior life already streamed, so
        the survivor's hub continues the same gapless sequence space.

        ``trace_id`` is the distributed-trace context
        (docs/OBSERVABILITY.md "Distributed tracing"): the id naming this
        session's whole cross-process journey, stamped onto every span
        and flight event that touches it and persisted in the spill
        manifest so a migrated resume CONTINUES the same trace.  The
        gateway passes the client's ``X-Trace-Id`` (or the router's
        minted one); None — the library default — adds no context and
        costs nothing.

        Validates exactly what the driver validates (2-D int8 board, every
        state within the rule's range, non-negative budget) and raises
        :class:`QueueFull` when the bounded queue is at capacity — the
        request is rejected before anything is stored, so backpressure
        bounds memory, not just slots.  After :meth:`begin_drain` every
        submit raises :class:`Draining` instead (admission is closed).

        Stochastic rules (``tpu_life.mc``): ``seed`` names the
        counter-based PRNG stream (default 0) and ``temperature`` is the
        per-session ising scalar — both ride in the batch slot, not the
        CompileKey, so a mixed-temperature sweep shares one compiled
        program.  A temperature on a non-ising rule, or a stochastic rule
        on an executor without the key schedule, is a typed rejection
        here — before anything is stored.

        ``start_step`` is the failover-resume field (docs/FLEET.md): the
        absolute steps a previous life of this trajectory already
        completed.  ``board`` is that life's last snapshot, ``steps`` the
        REMAINING budget; views report absolute progress and the MC
        engines re-enter the PRNG stream at ``start_step`` — so
        resume-then-finish equals the uninterrupted run bit-for-bit.
        """
        if isinstance(rule, str):
            rule = get_rule(rule)
        from tpu_life import mc

        mc.validate_params(rule, temperature)
        if rule.stochastic:
            # serve backends are always explicit (no "auto"), so the hard
            # gate applies directly — rejected before anything is stored
            mc.require_key_schedule(rule, self.config.backend)
            if seed is None:
                seed = 0
        if seed is not None:
            seed = int(seed)
        if rule.continuous:
            # the continuous tier (models/lenia.py): float32 boards in
            # [0, 1], finite — and only on the float executors.  The
            # "tuned" pseudo-backend passes here: make_engine resolves
            # it through the autotune cache and re-applies the gate on
            # whatever executor the cache actually names.
            from tpu_life.models import lenia

            if self.config.backend != "tuned":
                lenia.require_float_path(rule, self.config.backend)
            board = lenia.validate_board(board, rule)
        else:
            # validate BEFORE the int8 cast: a wider-dtype caller array
            # with state 256 would wrap to 0 and sail through a post-cast
            # check — simulated junk, not a rejection
            board = np.asarray(board)
            if board.ndim != 2:
                raise ValueError(f"board must be 2-D, got shape {board.shape}")
            max_state = int(board.max(initial=0))
            if max_state >= rule.states:
                raise ValueError(
                    f"board contains state {max_state} but rule {rule.name!r} "
                    f"has only {rule.states} states (0..{rule.states - 1})"
                )
            min_state = int(board.min(initial=0))
            if min_state < 0:
                # the driver's file codec cannot produce negatives, but a
                # library caller's array can — reject rather than simulate junk
                raise ValueError(
                    f"board contains negative state {min_state}; states are "
                    f"0..{rule.states - 1}"
                )
            board = board.astype(np.int8)
        # kernel-vs-board geometry (docs/RULES.md): a kernel wider than
        # the board is a typed rejection at every admission front
        from tpu_life.models.rules import validate_rule_geometry

        validate_rule_geometry(rule, board.shape)
        # board-area admission check against the PRNG counter width: the
        # packed engine carries the wide two-word cell index; the roll
        # engines are pinned narrow, so over-2^32-cell boards on them are
        # a typed rejection here, never a silent counter wraparound
        mc.validate_board_shape(
            rule,
            board.shape,
            wide_counter=mc.wide_counter_capable(
                rule, self.config.backend, bitpack=self.config.mc_packed
            ),
        )
        if steps < 0:
            raise ValueError(f"steps must be >= 0, got {steps}")
        start_step = int(start_step)
        if start_step < 0:
            raise ValueError(f"start_step must be >= 0, got {start_step}")
        stream_seq = int(stream_seq)
        if stream_seq < 0:
            raise ValueError(f"stream_seq must be >= 0, got {stream_seq}")
        # the steered-session resume logs: validated against THIS board's
        # geometry and rule before anything is stored
        edit_history = []
        for step, cells in parse_edit_log(edits if edits is not None else []):
            if step > start_step:
                raise ValueError(
                    f"applied edit at step {step} is past start_step "
                    f"{start_step}; unapplied edits belong in "
                    f"'scheduled_edits'"
                )
            edit_history.append((step, validate_cells(cells, board.shape, rule)))
        edit_scheduled = []
        for step, cells in parse_edit_log(
            scheduled_edits if scheduled_edits is not None else []
        ):
            if not start_step <= step < start_step + steps:
                raise ValueError(
                    f"scheduled edit at step {step} is outside this "
                    f"session's run [{start_step}, {start_step + steps})"
                )
            edit_scheduled.append(
                (step, validate_cells(cells, board.shape, rule))
            )
        # the mega-board mesh tier's resume pointer: validated against
        # the tile-set manifest BEFORE anything is stored, and minting
        # the session's mesh placement up front so the governor check
        # below runs against the mesh key
        mesh_shape: tuple[int, int] | None = None
        mesh_resume_rec = None
        if mesh_resume_dir is not None:
            mesh_resume_rec, mesh_shape = self._open_mesh_resume(
                mesh_resume_dir, rule, board.shape, steps, start_step
            )
        # admission is a read-modify-write on the queue: everything from the
        # backpressure check to the enqueue happens under the lock, so two
        # racing submits can neither both squeeze past a full queue nor
        # interleave with a pump's admit scan
        with self._lock:
            if self._draining:
                raise Draining(
                    "service is draining: no new sessions are admitted"
                )
            # tenant quotas (docs/SERVING.md "Tenant QoS"): the tenant's
            # own declared ceilings, checked before anything is stored.
            # A quota breach is the TENANT's limit, not service overload
            # — it stays out of the backpressure rejection counter and
            # lands in the per-tenant shed counter instead.
            if self._qos is not None and tenant is not None:
                spec = self._qos.spec(tenant)
                mine = self.store.live_by_tenant().get(tenant, 0)
                if (
                    spec.max_sessions is not None
                    and mine >= spec.max_sessions
                ):
                    self._quota_reject(tenant, "quota_sessions", trace_id)
                    raise QuotaExceeded(
                        f"tenant {tenant!r} already has {mine} live "
                        f"sessions; its max_sessions quota is "
                        f"{spec.max_sessions}",
                        tenant=tenant,
                        quota="max_sessions",
                        limit=spec.max_sessions,
                    )
                if (
                    spec.memory_fraction is not None
                    and self._memory_budget is not None
                ):
                    # the tenant's slice of the governor budget, charged
                    # per session at this session's engine estimate over
                    # capacity (a slot's share of its batch)
                    if mesh_shape is not None:
                        qkey = self._mesh_key(rule, board, mesh_shape)
                    else:
                        from tpu_life.ops.conv import resolve_stencil

                        qkey = compile_key_for(
                            rule,
                            board,
                            self.config.backend,
                            resolve_stencil(
                                rule, self.config.stencil, self.config.backend
                            ),
                        )
                    per = self._governor.estimate_engine_bytes(
                        qkey,
                        self.config.capacity,
                        mc_packed=self.config.mc_packed,
                    ) / max(1, self.config.capacity)
                    slice_bytes = spec.memory_fraction * self._memory_budget
                    if per * (mine + 1) > slice_bytes:
                        self._quota_reject(tenant, "quota_bytes", trace_id)
                        raise QuotaExceeded(
                            f"tenant {tenant!r} would hold "
                            f"~{int(per * (mine + 1))} estimated bytes; "
                            f"its budget slice is {int(slice_bytes)} "
                            f"({spec.memory_fraction:g} of "
                            f"{self._memory_budget})",
                            tenant=tenant,
                            quota="memory_fraction",
                            limit=int(slice_bytes),
                        )
            # the memory governor (docs/SERVING.md "Resource governance"):
            # would this session's CompileKey overflow the budget?  An
            # existing (or already-queued) key admits for free; a new key
            # must fit next to every reserved one.  Checked BEFORE the
            # session exists anywhere, so an XLA RESOURCE_EXHAUSTED
            # becomes a typed rejection instead of a dead worker.
            if self._memory_budget is not None:
                if mesh_shape is not None:
                    key = self._mesh_key(rule, board, mesh_shape)
                else:
                    from tpu_life.ops.conv import resolve_stencil

                    key = compile_key_for(
                        rule,
                        board,
                        self.config.backend,
                        resolve_stencil(
                            rule, self.config.stencil, self.config.backend
                        ),
                    )
                sched = self.scheduler
                reserved = self._governor.reserved_bytes(
                    sched.engines,
                    (self._keyer()(s) for s in sched.queue),
                    self.config.capacity,
                    mc_packed=self.config.mc_packed,
                )

                def _record_reject(e: InsufficientMemory) -> None:
                    if e.transient:
                        # transient pressure IS backpressure: it joins
                        # the classic rejection counter so the stats
                        # rejection_rate (the first overload signal)
                        # covers it; a never-fits session is a client
                        # error, not overload, and stays out
                        self._c_rejections.inc()
                    reason = (
                        "insufficient_memory"
                        if e.transient
                        else "session_too_large"
                    )
                    self._c_adm_rejected.labels(reason=reason).inc()
                    obs.flight.record(
                        "rejection", reason=reason, trace_id=trace_id
                    )

                try:
                    self._governor.check_admission(
                        key,
                        reserved,
                        self._memory_budget,
                        self.config.capacity,
                        mc_packed=self.config.mc_packed,
                        mesh_devices=self.config.mesh_devices,
                    )
                except InsufficientMemory as e:
                    # the mesh tier's conversion point (docs/SERVING.md
                    # "Mega-board sessions"): a never-fits verdict on a
                    # worker with a reserved slice is a PLACEMENT, not a
                    # rejection — re-mint the key as mesh:RxC (capacity
                    # 1, sharded over the slice) and re-run admission
                    # against the same reserved set
                    mesh_key = None
                    if (
                        not e.transient
                        and mesh_shape is None
                        and self.config.mesh_devices >= 2
                        and not rule.stochastic
                    ):
                        mesh_key, mesh_shape = self._plan_mesh_key(rule, board)
                    if mesh_key is None:
                        _record_reject(e)
                        raise
                    try:
                        self._governor.check_admission(
                            mesh_key,
                            reserved,
                            self._memory_budget,
                            1,
                            mc_packed=self.config.mc_packed,
                        )
                    except InsufficientMemory as e2:
                        mesh_shape = None
                        _record_reject(e2)
                        raise
                    obs.flight.record(
                        "mesh.placement",
                        trace_id=trace_id,
                        rule=rule.name,
                        mesh=f"{mesh_shape[0]}x{mesh_shape[1]}",
                        estimated_bytes=e.estimated_bytes,
                    )
            # backpressure check BEFORE the session exists anywhere; a bounce
            # is an admission outcome worth counting (rejection rate is the
            # first overload signal), so the counter ticks before the raise
            try:
                self.scheduler.ensure_admission()
            except QueueFull:
                self._c_rejections.inc()
                self._c_adm_rejected.labels(reason="queue_full").inc()
                obs.flight.record(
                    "rejection", reason="queue_full", trace_id=trace_id
                )
                raise
            now = self.clock()
            if timeout_s is None:
                timeout_s = self.config.default_timeout_s
            s = self.store.create(
                board=board.copy(),
                rule=rule,
                steps=steps,
                submitted_at=now,
                deadline=None if timeout_s is None else now + timeout_s,
                fault_at=fault_at,
                seed=seed,
                temperature=None if temperature is None else float(temperature),
                start_step=start_step,
                trace_id=trace_id,
                edits=edit_history,
                scheduled_edits=edit_scheduled,
                stream_seq=stream_seq,
                tenant=tenant,
            )
            if mesh_shape is not None:
                # the mega-board stamp: the keyer mints mesh:RxC from it,
                # the view renders it, the spill pass goes shard-wise
                s.mesh = mesh_shape
            if mesh_resume_rec is not None:
                # ownership transfer by rename (atomic on one filesystem):
                # the survivor's store adopts the tile set under the NEW
                # sid, so the session is durable from round one and the
                # victim-directory cleanup finds nothing left to delete.
                # A failed rename (cross-device) falls back to reading
                # the tiles in place.
                import dataclasses as _dc

                rec = mesh_resume_rec
                adopt = getattr(self._spill, "adopt_mesh", None)
                if adopt is not None:
                    new_root = adopt(s.sid, rec.root)
                    if new_root is not None:
                        rec = _dc.replace(rec, root=new_root)
                s.mesh_resume = rec.block_loader()
            # the admission flight event (docs/OBSERVABILITY.md): one
            # ring append per accepted session — what the doctor joins
            # the journey's start on.  start_step > 0 marks a resumed
            # (migrated) life of an existing trajectory.
            obs.flight.record(
                "admission",
                sid=s.sid,
                trace_id=trace_id,
                rule=s.rule.name,
                steps=steps,
                start_step=start_step,
            )
            if start_step > 0 and self._spill is not None:
                # spill-on-adopt (docs/FLEET.md): this submission carries a
                # RESCUED trajectory — until it is spilled HERE, a second
                # kill loses it (the PR 8 known limit).  Mark it urgent so
                # the very next spill-capable round writes it, cadence or
                # not, and back-to-back kills degrade to one extra rescue
                # instead of a 410 never_snapshotted.
                s.spill_urgent = True
                self._spill_urgent_pending += 1
            self._c_submitted.inc()
            if steps == 0:
                # nothing to run: complete at admission, never costs a slot
                s.finish(board.copy())
                self._c_finished.labels(state=s.state.value).inc()
                self._h_latency.observe(0.0)
                self._completed += 1
                # the journey still needs its terminal event: this branch
                # bypasses the scheduler (no session_finished hook), and
                # a doctor reading only the admission would flag a
                # cleanly-done session as no_terminal
                obs.flight.record(
                    "terminal",
                    sid=s.sid,
                    trace_id=trace_id,
                    outcome=s.state.value,
                    step=start_step,
                )
            else:
                self.scheduler.enqueue(s)
                # the per-session queue-wait interval: an async (overlapping)
                # trace span, closed at admission or terminal-in-queue —
                # carrying the trace context so the merged fleet timeline
                # shows WHOSE wait this was
                with obs.activate(self._tracer):
                    obs.async_begin(
                        "queue-wait", s.sid, steps=steps, trace_id=trace_id
                    )
        log.debug("serve: submitted %s (%s, %d steps)", s.sid, rule.name, steps)
        return s.sid

    def _quota_reject(self, tenant: str, reason: str, trace_id) -> None:
        """Account one typed tenant-quota rejection (docs/SERVING.md
        "Tenant QoS"): the admission-rejection reason row, the
        per-tenant shed counter, and the flight event the doctor joins."""
        self._c_adm_rejected.labels(reason=reason).inc()
        self._c_tenant_shed.labels(
            tenant=tenant_label(tenant), reason=reason
        ).inc()
        obs.flight.record(
            "rejection", reason=reason, tenant=tenant, trace_id=trace_id
        )

    def sweep(
        self,
        board: np.ndarray,
        rule: Rule | str,
        steps: int,
        temperatures,
        *,
        seed: int = 0,
        timeout_s: float | None = None,
    ) -> list[str]:
        """Fan a temperature grid into one session per temperature.

        The continuous-batching shape of a Monte-Carlo parameter sweep
        (ISSUE; arXiv:2412.14374's MPMD load): every session shares the
        same board, seed and rule, so they all land in ONE CompileKey and
        one compiled vmapped step — the per-slot acceptance tables are
        the only thing that differs.  Returns the session ids in
        temperature order.  Admission semantics are exactly N ``submit``
        calls: a full queue raises :class:`QueueFull` on the session that
        did not fit (earlier ones stay admitted — pump and resubmit).
        """
        temps = [float(t) for t in temperatures]
        if not temps:
            raise ValueError("sweep needs at least one temperature")
        return [
            self.submit(
                board,
                rule,
                steps,
                timeout_s=timeout_s,
                seed=seed,
                temperature=t,
            )
            for t in temps
        ]

    def poll(self, sid: str) -> SessionView:
        with self._lock:
            return self.store.view(sid)

    def result(self, sid: str) -> np.ndarray:
        with self._lock:
            return self.store.result(sid)

    def cancel(self, sid: str) -> bool:
        """Stop a session wherever it is; True if this call stopped it.

        Cancelling a RUNNING session frees its batch slot at the next
        round boundary semantics: the slot is released immediately, the
        engine's freeze mask stops stepping it, and the partial board is
        discarded (``steps_done`` records how far it got).
        """
        with self._lock:
            s = self.store.get(sid)
            if s.state in TERMINAL:
                return False
            if s.state is SessionState.QUEUED:
                self.scheduler.remove_queued(s)
            else:
                self.scheduler.evict_running(s)
            s.cancel()
            with obs.activate(self._tracer):
                self.session_finished(s, max(0.0, self.clock() - s.submitted_at))
            return True

    # -- mid-run steering + the streaming result channel --------------------
    def edit_cells(self, sid: str, cells) -> SessionView:
        """Apply a validated cell-mask to a live session between chunks
        (docs/STREAMING.md "Edits"): the PATCH verb behind
        ``/v1/sessions/{sid}/cells``.

        A QUEUED session's board is mutated in place (logged at
        ``start_step`` — the edit is part of the board the run starts
        from); a RUNNING session's edit is queued on the session and
        drained by the scheduler at the next round boundary through the
        freeze-mask seam (collect -> peek -> mutate -> reload), logged at
        the materialized step it lands on.  Every applied edit enters the
        session's edit log, which spills with the manifest — so the
        bit-reproducibility contract extends to steered sessions.  Typed
        ``ValueError`` on a terminal session, a session whose compute
        already finished, or a malformed mask.
        """
        with self._lock:
            s = self.store.get(sid)
            if s.state in TERMINAL:
                raise ValueError(
                    f"session {sid} is {s.state.value}; cannot edit a "
                    f"terminal session"
                )
            if s.state is SessionState.RUNNING and s.steps_remaining == 0:
                raise ValueError(
                    f"session {sid} has finished computing (awaiting "
                    f"retirement); cannot edit"
                )
            validated = validate_cells(cells, s.board.shape, s.rule)
            if s.state is SessionState.QUEUED:
                for r, c, v in validated:
                    s.board[r, c] = v
                s.edits.append((s.start_step, validated))
                with obs.activate(self._tracer):
                    self.session_edited(s, s.start_step, validated)
            else:
                s.pending_edits.append(validated)
            return self.store.view(sid)

    def stream_subscribe(self, sid: str, cursor: int = 0) -> None:
        """Register one watcher of ``sid``'s delta stream.

        The FIRST watcher of a session charges the stream's estimated
        ring bytes against the memory budget (docs/SERVING.md "Resource
        governance") — transient :class:`InsufficientMemory` when it
        does not fit next to the reserved engines, so a watcher storm
        backpressures typed instead of growing the worker until the OOM
        killer finds it.  Subscribing to an already-terminal session
        still yields a stream: one final keyframe plus the ``end`` frame.
        """
        with self._lock:
            s = self.store.get(sid)  # UnknownSession -> 404 upstream
            if sid not in self._stream_charged:
                # tenant watcher-buffer quota (docs/SERVING.md "Tenant
                # QoS"): a NEW session ring counts against its tenant's
                # max_watchers before any bytes are charged
                if self._qos is not None and s.tenant is not None:
                    spec = self._qos.spec(s.tenant)
                    if spec.max_watchers is not None:
                        mine = 0
                        for other in self._stream_charged:
                            o = self.store._sessions.get(other)
                            if o is not None and o.tenant == s.tenant:
                                mine += 1
                        if mine >= spec.max_watchers:
                            self._quota_reject(
                                s.tenant, "quota_watchers", s.trace_id
                            )
                            raise QuotaExceeded(
                                f"tenant {s.tenant!r} already holds "
                                f"{mine} watcher buffers; its "
                                f"max_watchers quota is "
                                f"{spec.max_watchers}",
                                tenant=s.tenant,
                                quota="max_watchers",
                                limit=spec.max_watchers,
                            )
                est = estimate_stream_bytes(
                    s.board.shape, str(s.board.dtype), self.hub.ring_frames
                )
                if self._memory_budget is not None:
                    reserved = sum(
                        self._governor.reserved_bytes(
                            self.scheduler.engines,
                            (self._keyer()(q) for q in self.scheduler.queue),
                            self.config.capacity,
                            mc_packed=self.config.mc_packed,
                        ).values()
                    )
                    charged = sum(self._stream_charged.values())
                    if reserved + charged + est > self._memory_budget:
                        self._c_rejections.inc()
                        self._c_adm_rejected.labels(
                            reason="watcher_buffer"
                        ).inc()
                        obs.flight.record(
                            "rejection",
                            reason="watcher_buffer",
                            sid=sid,
                            trace_id=s.trace_id,
                        )
                        raise InsufficientMemory(
                            f"watcher buffer for {sid} needs ~{est} bytes "
                            f"next to {reserved + charged} reserved; budget "
                            f"is {self._memory_budget}",
                            transient=True,
                            estimated_bytes=est,
                            budget_bytes=self._memory_budget,
                        )
                self._stream_charged[sid] = est
            self.hub.subscribe(sid, start_seq=s.stream_seq)
            if s.state in TERMINAL:
                step = s.start_step + s.steps_done
                if s.state is SessionState.DONE and s.result is not None:
                    self.hub.produce(
                        sid, s.result, step, executor=self.config.backend
                    )
                self.hub.finish(sid, s.state.value, step)

    def stream_read(
        self, sid: str, cursor: int, timeout: float | None = 0.25
    ) -> tuple[list, int, bool]:
        """Blocking frame read — NO service lock held (the hub has its
        own condition), so a watcher waiting on frames never delays
        submit/poll/cancel or the pump.  Returns
        ``(frames, next_cursor, eof)``."""
        return self.hub.read(sid, cursor, timeout)

    def stream_unsubscribe(self, sid: str) -> None:
        with self._lock:
            if self.hub.unsubscribe(sid):
                # last watcher gone: the ring state was discarded, so the
                # governor charge is released with it
                self._stream_charged.pop(sid, None)

    def _produce_frames(self) -> None:
        """The pump's frame tap (locked, both pump shapes): one hub
        append per watched session per round, read from each engine's
        double buffer (``peek_slot`` — the materialized board at
        ``start_step + steps_done - lag``; never waits on the in-flight
        chunk).  Queued watched sessions get their initial keyframe from
        the submitted board, so a watcher sees the start state while the
        session still waits for a slot."""
        if not self.hub.active():
            return
        sched = self.scheduler
        for key, slots in sched.running.items():
            engine = sched.engines.get(key)
            if engine is None:
                continue
            label = f"{key.backend}:{type(engine).__name__}"
            for slot, s in list(slots.items()):
                if not self.hub.wants(s.sid):
                    continue
                try:
                    board, lag = engine.peek_slot(slot)
                except recovery.RECOVERABLE:
                    continue  # the recovery path owns this engine now
                self.hub.produce(
                    s.sid,
                    np.asarray(board),
                    s.start_step + s.steps_done - lag,
                    executor=label,
                )
        for s in sched.queue:
            if self.hub.wants(s.sid):
                self.hub.produce(
                    s.sid, s.board, s.start_step, executor="queued"
                )

    # -- scheduler telemetry observer ---------------------------------------
    def session_admitted(self, session, wait_s: float) -> None:
        """Scheduler hook: a session got its batch slot after ``wait_s``."""
        self._h_queue_wait.observe(wait_s)
        obs.async_end("queue-wait", session.sid)
        # the per-session execution interval (docs/OBSERVABILITY.md
        # "Distributed tracing"): an async b/e pair from slot admission
        # to the terminal transition, keyed by sid and stamped with the
        # trace context — the interval the doctor's no-double-execution
        # invariant compares across worker incarnations.  A salvage-
        # reloaded session (engine recovery) re-begins under the same id;
        # Perfetto nests re-begins and the doctor keys on the outer pair.
        obs.async_begin(
            "serve.exec",
            session.sid,
            trace_id=session.trace_id,
            step=session.start_step + session.steps_done,
        )

    def session_edited(self, session, step: int, cells) -> None:
        """Scheduler hook: an edit-log entry was applied to ``session``
        at absolute ``step`` (also called directly for QUEUED edits).
        The stream mirrors it as a metadata frame; the flight ring keeps
        the steering decision for postmortems."""
        self.hub.record_edit(session.sid, step, cells)
        obs.flight.record(
            "edit",
            sid=session.sid,
            trace_id=session.trace_id,
            step=step,
            cells=len(cells),
        )
        obs.instant(
            "serve.session.edit",
            sid=session.sid,
            trace_id=session.trace_id,
            step=step,
            cells=len(cells),
        )

    def session_finished(self, session, latency_s: float) -> None:
        """Scheduler hook: a session reached a terminal state (done /
        failed / cancelled) ``latency_s`` after submission."""
        self._c_finished.labels(state=session.state.value).inc()
        self._h_latency.observe(latency_s)
        if self.hub.wants(session.sid):
            # close the stream: a DONE session's watchers get the final
            # board (keyframe) then the terminal frame; failed/cancelled
            # get the terminal frame alone — every read drains to EOF
            step = session.start_step + session.steps_done
            if (
                session.state is SessionState.DONE
                and session.result is not None
            ):
                self.hub.produce(
                    session.sid,
                    session.result,
                    step,
                    executor=self.config.backend,
                )
            self.hub.finish(session.sid, session.state.value, step)
        if self._spill is not None:
            # a terminal session must never resume: its spill dies with it
            self._spill.delete(session.sid)
        if session.admitted_at is None:
            # it died waiting: close the still-open queue-wait interval
            obs.async_end("queue-wait", session.sid, outcome=session.state.value)
        else:
            obs.async_end(
                "serve.exec",
                session.sid,
                trace_id=session.trace_id,
                outcome=session.state.value,
                step=session.start_step + session.steps_done,
            )
        obs.flight.record(
            "terminal",
            sid=session.sid,
            trace_id=session.trace_id,
            outcome=session.state.value,
            step=session.start_step + session.steps_done,
        )

    def engine_recovered(self, key, outcome: str) -> None:
        """Scheduler hook: a chunk-level fault on ``key`` was handled —
        masked in place (``replayed`` / the OOM ladder rungs) or, past
        the restart budget, failed typed (``budget_exhausted``)."""
        self._c_recoveries.labels(outcome=outcome).inc()
        bucket = _key_bucket(key)
        obs.instant("serve.recovery", compile_key=bucket, outcome=outcome)
        obs.flight.record("recovery", compile_key=bucket, outcome=outcome)

    def drain(self, max_rounds: int | None = None) -> int:
        """Pump until every admitted session reaches a terminal state;
        returns the number of rounds run.  ``max_rounds`` bounds a stuck
        drain (it raises rather than spinning forever)."""
        rounds = 0
        with obs.activate(self._tracer), maybe_profile(self.config.profile):
            while not self.idle():
                self.pump()
                rounds += 1
                if max_rounds is not None and rounds >= max_rounds:
                    if not self.idle():
                        raise RuntimeError(
                            f"drain did not converge in {max_rounds} rounds "
                            f"({len(self.scheduler.queue)} queued)"
                        )
                    break
        return rounds

    def begin_drain(self) -> None:
        """Close admission (every later ``submit`` raises :class:`Draining`)
        while in-flight sessions keep running — the graceful-shutdown hook.
        The caller still pumps (or ``drain()``s) to completion and then
        ``close()``s; this only flips the admission valve."""
        with self._lock:
            if not self._draining:
                self._draining = True
                log.info("serve: draining — admission closed")

    @property
    def draining(self) -> bool:
        return self._draining

    def rebind_spill(self, namespace: str) -> None:
        """Re-point a REMOTE spill backend at a fresh incarnation
        namespace (docs/FLEET.md "Cross-host topology"): a wire-registered
        worker calls this when the control plane grants it a new
        ``(worker, generation)`` — its spills must land in the namespace
        the migrator will read for THAT incarnation.  Typed error on a
        local (or absent) backend: only the HTTP store has namespaces."""
        if self._spill is None or not hasattr(self._spill, "set_namespace"):
            raise ValueError(
                "rebind_spill needs a remote spill backend (spill_url)"
            )
        self._spill.set_namespace(namespace)

    def cancel_live(self, reason: str = "cancelled") -> int:
        """Cancel every non-terminal session; returns how many.  The
        fenced-worker recourse (docs/FLEET.md): a worker refused with
        ``lease_expired`` learned its sessions were RESCUED elsewhere —
        finishing its local copies would double-execute trajectories the
        fleet already re-homed, so it drops them before re-registering."""
        with self._lock:
            sids = [s.sid for s in self.store.live()]
        n = sum(1 for sid in sids if self.cancel(sid))
        if n:
            log.warning("serve: cancelled %d live session(s): %s", n, reason)
        return n

    def idle(self) -> bool:
        """True when nothing is queued or resident in any batch slot."""
        with self._lock:
            return self.scheduler.idle()

    # -- the scheduling quantum -------------------------------------------
    def pump(self) -> RoundStats:
        """One scheduling round; the only place device work happens.

        The pipelined pump (default) holds the service lock only for its
        begin/end phases — the settle window, where device chunks and
        host-engine compute actually finish, runs unlocked so ``submit``
        and ``poll`` are never blocked behind device work.  The sync pump
        holds the lock for the whole round (the classic seam: handlers
        never touch engines, the pump never sees a half-enqueued session).
        """
        if not self.config.pipeline:
            with self._lock:
                return self._pump_locked()
        with self._pump_mutex:
            return self._pump_pipelined()

    def _keyer(self):
        cfg = self.config
        from tpu_life.ops.conv import resolve_stencil

        def keyer(s) -> CompileKey:
            if getattr(s, "mesh", None) is not None:
                return self._mesh_key(s.rule, s.board, s.mesh)
            return compile_key_for(
                s.rule,
                s.board,
                cfg.backend,
                resolve_stencil(s.rule, cfg.stencil, cfg.backend),
            )

        return keyer

    # -- the mega-board mesh tier (docs/SERVING.md "Mega-board sessions") --
    def _mesh_key(self, rule, board, mesh_shape) -> CompileKey:
        """The ``mesh:RxC`` CompileKey for a placed mega-board.  The
        stencil resolves against the device-backend crossover model (the
        sharded scan compiles the same XLA stencil the jax executor
        does), so a mega-board Lenia takes the banded-matmul path."""
        from tpu_life.ops.conv import resolve_stencil
        from tpu_life.serve.mesh_engine import mesh_backend_name

        return compile_key_for(
            rule,
            board,
            mesh_backend_name(mesh_shape),
            resolve_stencil(rule, self.config.stencil, "jax"),
        )

    def _plan_mesh_key(self, rule, board):
        """``(mesh_key, mesh_shape)`` for a never-fits board on this
        worker's reserved slice, or ``(None, None)`` when no legal mesh
        factorization exists (the rejection then stands, carrying the
        mesh_eligible hint for a bigger fleet)."""
        from tpu_life.serve.mesh_engine import plan_mesh_shape

        shape = plan_mesh_shape(self.config.mesh_devices, board.shape, rule)
        if shape is None:
            return None, None
        return self._mesh_key(rule, board, shape), shape

    def _open_mesh_resume(self, mesh_resume_dir, rule, board_shape, steps, start_step):
        """Validate a shard-wise resume pointer against its tile-set
        manifest and this request; returns ``(record, mesh_shape)``.
        Raises ValueError (a typed 400 at the gateway) on any mismatch —
        before anything is stored."""
        from tpu_life.serve.mesh_engine import plan_mesh_shape
        from tpu_life.serve.spill import read_mesh_session_dir

        if self.config.mesh_devices < 2:
            raise ValueError(
                "mesh_resume_dir needs a worker with a reserved mesh "
                "slice (mesh_devices >= 2); this worker has "
                f"{self.config.mesh_devices}"
            )
        if rule.stochastic:
            raise ValueError(
                f"rule {rule.name!r} is stochastic: the mesh tier has no "
                "sharded Monte-Carlo path"
            )
        if steps < 1:
            raise ValueError("mesh_resume_dir with steps == 0 has nothing to run")
        rec = read_mesh_session_dir(mesh_resume_dir)
        if get_rule(rec.rule).name != rule.name:
            raise ValueError(
                f"tile set at {mesh_resume_dir} was spilled under rule "
                f"{rec.rule!r}, not {rule.name!r}"
            )
        if (rec.height, rec.width) != tuple(board_shape):
            raise ValueError(
                f"tile set at {mesh_resume_dir} is "
                f"{rec.height}x{rec.width}, not "
                f"{board_shape[0]}x{board_shape[1]}"
            )
        if int(start_step) != rec.step:
            raise ValueError(
                f"tile set's resumable epoch is step {rec.step}; "
                f"start_step {start_step} does not match"
            )
        shape = plan_mesh_shape(self.config.mesh_devices, board_shape, rule)
        if shape is None:
            raise ValueError(
                f"no legal {self.config.mesh_devices}-device mesh "
                f"factorization for a {board_shape[0]}x{board_shape[1]} "
                f"{rule.name} board"
            )
        return rec, shape

    def _pump_locked(self) -> RoundStats:
        with obs.activate(self._tracer), obs.span(
            "serve.round", round=self._rounds, pump="sync"
        ):
            stats = self.scheduler.round(self._keyer())
            self._produce_frames()
            plan = self._spill_plan()
            if plan:
                # the sync pump is fully settled after round(): every lag
                # is zero and every board materialized.  Spilling here
                # holds the lock (the sync pump holds it anyway).
                failures = self._run_spill(plan)
                self._apply_spill_failures(failures)
                self._sweep_spills(plan)
        self._finish_round(stats)
        return stats

    def _pump_pipelined(self) -> RoundStats:
        keyer = self._keyer()
        stats = RoundStats()
        with self._lock:
            with obs.activate(self._tracer), obs.span(
                "serve.round", round=self._rounds, pump="pipelined"
            ):
                plan = self.scheduler.round_begin(keyer, stats)
                rolled = {key for key, _, r in plan if r}
                for _, engine, _ in plan:
                    engine.busy = True
                # the spill plan is captured under the lock (the running
                # map is verb-mutable) but WRITTEN after settle, outside
                # it — durability must not block submit/poll/cancel
                spill_plan = self._spill_plan()
        # -- the overlap window: no service lock held.  Device chunks (and
        # host-engine compute) complete here while submit/poll/cancel stay
        # serviceable; verb-triggered slot releases defer to the next begin.
        spill_failures: list = []
        chunk_faults: list = []
        settled: list = []
        faulted: list = []
        # publish the settle window for the wedge watchdog: it reads
        # (start, plan, settled-so-far, faulted-so-far) from outside the
        # pump and fires once an engine blocks past settle_deadline_s
        # WITHOUT progress (each settled engine restarts the clock —
        # many keys legitimately settling in sequence is not a wedge)
        self._settle_state = (time.monotonic(), plan, settled, faulted)
        try:
            with obs.activate(self._tracer), obs.span(
                "serve.collect", engines=len(plan)
            ):
                for key, engine, was_rolled in plan:
                    try:
                        if was_rolled:
                            engine.settle()
                        else:
                            engine.collect_chunk()
                    except recovery.RECOVERABLE as e:
                        # a chunk-level fault while settling (the chaos
                        # engine.collect drill, or a real device reset):
                        # recorded here, RECOVERED under the lock below —
                        # rebuild + replay, this pump round survives.
                        # NOT marked settled: the wedge salvage must
                        # never fetch from an engine whose chunk just
                        # died (recover_engine owns its sessions).
                        chunk_faults.append((key, e))
                        faulted.append(key)
                    else:
                        settled.append(key)
            # the watchdog window closes HERE: every device wait is done.
            # The spill pass below is disk I/O — slow storage must never
            # read as a wedged device grant
            self._settle_state = None
            if spill_plan:
                # engines are settled (double buffers materialized) and
                # still marked busy, so verb releases stay deferred and
                # every peek reads stable state; a session cancelled
                # during the write is swept under the lock below.  The
                # tracer is re-activated: this runs outside the round's
                # activate block, and the spill span belongs to THIS
                # service's timeline, not whatever is ambient.
                with obs.activate(self._tracer):
                    spill_failures = self._run_spill(spill_plan)
        finally:
            self._settle_state = None
            with self._lock:
                for _, engine, _ in plan:
                    engine.busy = False
        with self._lock:
            with obs.activate(self._tracer):
                for key, exc in chunk_faults:
                    self.scheduler.recover_engine(key, exc, stats)
                self.scheduler.round_end(keyer, stats, rolled)
                self._produce_frames()
            if spill_plan:
                self._apply_spill_failures(spill_failures)
                self._sweep_spills(spill_plan)
            self._finish_round(stats)
        return stats

    # -- the wedge watchdog (docs/SERVING.md "Resource governance") ---------
    @property
    def wedged(self) -> dict | None:
        """The wedge verdict: None while healthy, else a machine-readable
        dict (``reason`` / ``compile_key`` / ``deadline_s`` /
        ``waited_s``) — what ``/readyz`` serializes into its 500 body.
        Sticky by design: a declared wedge means the settle-deadline
        contract was broken, and the supervisor recycle path owns the
        recovery from here."""
        return self._wedged

    def _watchdog_loop(self) -> None:
        deadline = float(self.config.settle_deadline_s)
        poll = max(0.01, min(0.25, deadline / 4))
        # progress tracking: the deadline applies to ONE engine's wait,
        # not the cumulative multi-engine window — every engine that
        # settles (or faults into the recovery path) restarts the clock,
        # so N keys legitimately settling in sequence never trip it
        last_state: tuple | None = None
        last_progress = -1
        baseline = 0.0
        while not self._watchdog_stop.wait(poll):
            state = self._settle_state
            if state is None or self._wedged is not None:
                last_state = None
                continue
            started, plan, settled, faulted = state
            progress = len(settled) + len(faulted)
            now = time.monotonic()
            if state is not last_state:
                last_state, last_progress, baseline = state, progress, started
            elif progress != last_progress:
                last_progress, baseline = progress, now
            if now - baseline <= deadline:
                continue
            waited = now - baseline
            # the stuck pump does NOT hold the service lock during the
            # settle window — that is the whole design of the pipelined
            # pump — so the watchdog can take it and act
            with self._lock:
                if self._settle_state is not state or self._wedged is not None:
                    continue  # the window closed while we queued
                skip = set(settled) | set(faulted)
                # the engine actually blocked: the first plan entry that
                # neither settled nor faulted (a faulted key already
                # failed over to recover_engine — blaming it would put
                # the wrong compile_key in the operator-facing verdict)
                stuck = next((k for k, _, _ in plan if k not in skip), None)
                if stuck is None:
                    # every engine settled or faulted: the window is
                    # logically over even if the pump has not cleared the
                    # state yet — nothing is wedged on a device
                    continue
                self._wedged = {
                    "reason": "settle_deadline",
                    "compile_key": (
                        _key_bucket(stuck) if stuck is not None else None
                    ),
                    "deadline_s": deadline,
                    "waited_s": waited,
                }
                self._c_recoveries.labels(outcome="wedged").inc()
                obs.flight.record("wedge", **self._wedged)
                # salvage only from SETTLED engines — a faulted engine's
                # chunk died and recover_engine owns its sessions.  NO
                # obs.activate here: the tracer's active slot is one
                # process global, and the wedged pump is still inside
                # its own activate scope on another thread — nesting a
                # second scope from the watchdog races the restore and
                # can leak (or drop) the active tracer.  The salvaged
                # sessions' terminal evidence rides the flight ring
                # instead (session_finished records it unconditionally;
                # the ring is lock-protected and activate-independent),
                # which is what the doctor reads outcomes from.
                salvaged = self._salvage_wedged_locked(plan, set(settled))
            log.error(
                "serve: WEDGED — settle window blocked %.1fs (deadline "
                "%.1fs) on %s; %d finisher(s) salvaged, /readyz now "
                "answers 500 engine_wedged so the supervisor's "
                "unready-recycle + migration path rescues the sessions",
                waited,
                deadline,
                self._wedged["compile_key"],
                salvaged,
            )

    def _salvage_wedged_locked(self, plan, settled: set) -> int:
        """Retire the pending finishers of engines that SETTLED before
        the wedge: their double buffers are materialized and the stuck
        pump is blocked in a different engine, so fetching them here
        (under the service lock) is safe — those results leave the
        worker before the supervisor recycles it."""
        sched = self.scheduler
        stats = RoundStats()
        for key, engine, _ in plan:
            if key not in settled:
                continue
            entries = sched.pending.get(key) or []
            slots = sched.running.get(key, {})
            for slot, s in list(entries):
                if slots.get(slot) is not s:
                    continue  # cancelled/expired meanwhile
                sched._retire_slot(engine, slots, slot, s, stats)
            sched.pending.pop(key, None)
        self._completed += stats.completed
        return stats.completed

    # -- durable sessions: the spill pass (docs/SERVING.md) -----------------
    def _spill_plan(self) -> list | None:
        """Locked: decide whether this round spills and capture what —
        ``(session, engine, slot)`` for every resident slot (engine=None
        for queued sessions, whose board is still the submitted copy)."""
        if self._spill is None:
            return None
        self._rounds_since_spill += 1
        due = self._rounds_since_spill >= self.config.spill_every
        if due:
            self._rounds_since_spill = 0
        elif self._spill_urgent_pending == 0:
            return None  # off-cadence, nothing urgent: the cheap path
        plan = []
        # an URGENT session (a just-adopted rescue, spill-on-adopt) rides
        # every round until its first successful write, cadence or not —
        # between resume-accept and that write, a second kill would lose
        # a trajectory a client was already promised survives kills
        urgent = 0
        for key, slots in self.scheduler.running.items():
            engine = self.scheduler.engines[key]
            for slot, s in slots.items():
                if not s.spill_disabled and (due or s.spill_urgent):
                    plan.append((s, engine, slot))
                    urgent += s.spill_urgent
        for s in self.scheduler.queue:
            if not s.spill_disabled and (due or s.spill_urgent):
                plan.append((s, None, None))
                urgent += s.spill_urgent
        # the walk recomputes the truth: spent/terminal urgencies drop out
        self._spill_urgent_pending = urgent
        return plan or None

    def _run_spill(self, plan: list) -> list:
        """Pump thread, engines settled: write each planned session's
        newest materialized board + manifest through the checkpoint
        contract.  Sessions that went terminal since the plan was taken
        are skipped (and swept under the lock afterwards).  Returns the
        ``(session, error)`` write failures — an ENOSPC (or any OSError)
        must NOT escape into the pump (it would kill the whole worker
        over one session's durability); the locked round tail degrades
        those sessions to spill-disabled instead."""
        t0 = time.monotonic()
        now = self.clock()
        failures: list = []
        with obs.span("serve.spill", sessions=len(plan)):
            for s, engine, slot in plan:
                if s.state in TERMINAL or s.spill_disabled:
                    continue
                if getattr(s, "mesh", None) is not None:
                    # mega-board sessions spill shard-wise (docs/SERVING.md
                    # "Mega-board sessions") — never through the
                    # full-board path, which would gather the one thing
                    # the tier exists to never materialize
                    err = self._spill_mesh(s, engine, slot, now)
                    if err is not None:
                        failures.append((s, err))
                    continue
                if engine is None:
                    board, lag = s.board, 0
                else:
                    board, lag = engine.peek_slot(slot)
                abs_step = s.start_step + s.steps_done - lag
                timeout_s = (
                    None if s.deadline is None else max(0.0, s.deadline - now)
                )
                # the steered-session manifest fields (docs/STREAMING.md):
                # the applied edit log (bit-reproducibility provenance),
                # the not-yet-applied tail a survivor must re-apply at
                # exactly the recorded steps, and the stream-sequence
                # floor a reconnected watcher stays gapless under.  Both
                # lists are pump-thread-private (apply_edits mutates them
                # in the locked begin phase, this pass runs on the same
                # thread), so reading them unlocked is safe.
                edits = render_edit_log(s.edits) or None
                scheduled = render_edit_log(s.scheduled_edits) or None
                try:
                    self._spill.save(
                        s.sid,
                        board,
                        abs_step,
                        rule=s.rule.name,
                        steps_total=s.start_step + s.steps,
                        seed=s.seed,
                        temperature=s.temperature,
                        timeout_s=timeout_s,
                        trace_id=s.trace_id,
                        edits=edits,
                        scheduled_edits=scheduled,
                        stream_seq=self.hub.seq_snapshot(
                            s.sid, default=s.stream_seq
                        ),
                    )
                    # the per-session durability marker: WHICH recovery
                    # point this trace now has (instant() is a no-op
                    # without an active tracer — one global check)
                    obs.instant(
                        "serve.session.spill",
                        sid=s.sid,
                        trace_id=s.trace_id,
                        step=abs_step,
                    )
                    # the adopted trajectory is durable again: the
                    # spill-on-adopt urgency is spent (a plain bool flip —
                    # benign against the locked plan capture; the worst
                    # race costs one redundant spill next round)
                    s.spill_urgent = False
                except OSError as e:
                    # the disk work of the degradation (drop the stale
                    # snapshots, publish the DISABLED marker) happens
                    # HERE, in the pump's unlocked window — a full or
                    # HUNG disk must never stall the service lock; the
                    # locked tail only flips the flag and the counter.
                    # A session that goes terminal meanwhile is swept
                    # (marker and all) by _sweep_spills, like any spill.
                    self._spill.mark_disabled(s.sid)
                    failures.append((s, e))
        dt = time.monotonic() - t0
        self._h_snapshot.observe(dt)
        self._snapshot_s_total += dt
        return failures

    def _spill_mesh(self, s, engine, slot, now) -> Exception | None:
        """Shard-wise spill of one mega-board session (pump thread,
        unlocked): walk the engine's addressable shards and persist one
        tile per shard through the store's tile contract — each host
        writes only its own bytes.  Returns the failure (for the locked
        degradation tail) instead of raising, like the board path.

        Skips silently while the session is still QUEUED (engine=None):
        a mesh board only becomes spillable once it is resident on its
        slice — the submitted copy is either the client's resubmittable
        request or, on a resume, a geometry placeholder that must never
        overwrite good tiles."""
        if engine is None or not hasattr(engine, "spill_tiles"):
            return None
        if not getattr(self._spill, "SUPPORTS_MESH", False):
            # the remote HTTP store has no tile contract (yet): shipping
            # a gathered mega-board over it would defeat the tier, so
            # durability degrades for this session alone — the same
            # contract as a failed write, and just as visible
            self._spill.mark_disabled(s.sid)
            return OSError(
                "spill backend has no shard-wise tile contract "
                "(mesh sessions need a local spill_dir)"
            )
        try:
            tiles, lag = engine.spill_tiles(slot)
            abs_step = s.start_step + s.steps_done - lag
            timeout_s = (
                None if s.deadline is None else max(0.0, s.deadline - now)
            )
            self._spill.save_mesh(
                s.sid,
                tiles,
                abs_step,
                rule=s.rule.name,
                steps_total=s.start_step + s.steps,
                seed=s.seed,
                temperature=s.temperature,
                timeout_s=timeout_s,
                height=int(s.board.shape[0]),
                width=int(s.board.shape[1]),
                mesh=s.mesh,
                trace_id=s.trace_id,
                edits=render_edit_log(s.edits) or None,
                scheduled_edits=render_edit_log(s.scheduled_edits) or None,
                stream_seq=self.hub.seq_snapshot(s.sid, default=s.stream_seq),
            )
            obs.instant(
                "serve.session.spill",
                sid=s.sid,
                trace_id=s.trace_id,
                step=abs_step,
                mesh=f"{s.mesh[0]}x{s.mesh[1]}",
                tiles=len(tiles),
            )
            s.spill_urgent = False
            return None
        except OSError as e:
            self._spill.mark_disabled(s.sid)
            return e

    def _apply_spill_failures(self, failures: list) -> None:
        """Locked: degrade each failed write's session to spill-disabled —
        one counter tick and ONE log line per session (it leaves the spill
        plan, so it can never re-fail or re-log).  The DISABLED marker
        was already published by the unlocked spill pass; the session
        itself keeps running: a full disk costs durability, never the
        service."""
        for s, e in failures:
            if s.spill_disabled:
                continue
            s.spill_disabled = True
            self._c_spill_errors.inc()
            obs.flight.record(
                "spill_disabled", sid=s.sid, trace_id=s.trace_id, error=str(e)
            )
            log.warning(
                "serve: spill write for %s failed (%s); durability disabled "
                "for this session — it keeps running without failover cover",
                s.sid,
                e,
            )

    def _sweep_spills(self, plan: list) -> None:
        """Locked: drop spills of sessions that reached a terminal state
        while (or after) the unlocked spill pass wrote them — closes the
        cancel-races-the-writer window, so no terminal session ever
        leaves a resumable spill behind."""
        for s, _, _ in plan:
            if s.state in TERMINAL:
                self._spill.delete(s.sid)

    def _finish_round(self, stats: RoundStats) -> None:
        """The locked round tail shared by both pump shapes: counters,
        gauges, the per-round metrics record, the live prom snapshot."""
        self._completed += stats.completed
        self._rounds += 1
        self._steps_total += stats.steps_advanced
        self._steps_packed_total += stats.steps_advanced_packed
        self._c_rounds.inc()
        if stats.steps_advanced:
            self._c_steps.inc(stats.steps_advanced)
        if stats.steps_advanced_packed:
            self._c_steps_packed.inc(stats.steps_advanced_packed)
        occ = stats.occupancy / stats.slots if stats.slots else 0.0
        self._occupancy_sum += occ
        self._g_queue_depth.set(stats.queue_depth)
        self._g_queue_age.set(self.scheduler.queue_age_oldest_s())
        self._g_occupancy.set(occ)
        depth = sum(1 for e in self.scheduler.engines.values() if e.inflight)
        self._g_pipeline_depth.set(depth)
        matmul_keys = sum(
            1
            for e in self.scheduler.engines.values()
            if getattr(e, "stencil", None) == "matmul"
        )
        self._g_matmul_keys.set(float(matmul_keys))
        idle_delta = self.scheduler.idle_seconds_delta()
        if idle_delta > 0:
            self._c_device_idle.inc(idle_delta)
        if self._spill is not None:
            self._g_spilled.set(float(self._spill.spilled_count()))
        if self._tracer is not None:
            # fold ring evictions into the loss counter (monotone: the
            # tracer's dropped count only grows; we tick the delta)
            dropped = self._tracer.dropped
            if dropped > self._trace_dropped_seen:
                self._c_trace_dropped.inc(dropped - self._trace_dropped_seen)
                self._trace_dropped_seen = dropped
        # mirror the stream hub's plain-int totals into the registry as
        # monotone deltas (same pattern as the trace-drop fold above)
        frames_now = self.hub.frames_total
        if frames_now > self._stream_frames_seen:
            self._c_stream_frames.inc(frames_now - self._stream_frames_seen)
            self._stream_frames_seen = frames_now
        gaps_now = self.hub.gaps_total
        if gaps_now > self._stream_gaps_seen:
            self._c_stream_gaps.inc(gaps_now - self._stream_gaps_seen)
            self._stream_gaps_seen = gaps_now
        stream_watchers = self.hub.watcher_count()
        self._g_stream_watchers.set(float(stream_watchers))
        for key, count in self.scheduler.compile_counts().items():
            self._g_compiles.labels(compile_key=_key_bucket(key)).set(count)
        # the governor's footprint view: what each live engine is charged
        # against the budget (same bounded key buckets as compile counts).
        # Unlike compile counts this is a LIVE footprint, so buckets of
        # released engines zero out instead of showing a stale charge.
        live_buckets = set()
        for key in self.scheduler.engines:
            bucket = _key_bucket(key)
            live_buckets.add(bucket)
            self._g_est_bytes.labels(key=bucket).set(
                float(
                    self._governor.estimate_engine_bytes(
                        key,
                        self.config.capacity,
                        mc_packed=self.config.mc_packed,
                    )
                )
            )
        for bucket in self._est_buckets - live_buckets:
            self._g_est_bytes.labels(key=bucket).set(0.0)
        self._est_buckets = live_buckets
        # the mesh tier's observability rows (docs/SERVING.md "Mega-board
        # sessions"): live mesh-sharded sessions, and the governor's
        # per-shard estimator rows for every live mesh engine — stale
        # (key, shard) rows zero out when the engine goes, like the
        # per-key footprint above
        mesh_sessions = sum(
            len(slots)
            for key, slots in self.scheduler.running.items()
            if str(getattr(key, "backend", "")).startswith("mesh:")
        )
        self._g_mesh_sessions.set(float(mesh_sessions))
        live_mesh = set()
        for key, e in self.scheduler.engines.items():
            shape = getattr(e, "mesh_shape", None)
            if shape is None:
                continue
            bucket = _key_bucket(key)
            for shard, per in self._governor.estimate_mesh_shard_bytes(
                key, shape
            ).items():
                live_mesh.add((bucket, shard))
                self._g_mesh_est_bytes.labels(key=bucket, shard=shard).set(
                    float(per)
                )
        for bucket, shard in self._mesh_est_buckets - live_mesh:
            self._g_mesh_est_bytes.labels(key=bucket, shard=shard).set(0.0)
        self._mesh_est_buckets = live_mesh
        # the per-tenant session rows (docs/SERVING.md "Tenant QoS"):
        # live counts per tenant label, stale buckets zeroed like the
        # governor footprint above.  Policy-less services skip the walk
        # entirely — zero label cardinality, zero cost.
        if self._qos is not None:
            live_tenants = set()
            for name, n in self.store.live_by_tenant().items():
                lbl = tenant_label(name)
                live_tenants.add(lbl)
                self._g_tenant_sessions.labels(tenant=lbl).set(float(n))
            for lbl in self._tenant_buckets - live_tenants:
                self._g_tenant_sessions.labels(tenant=lbl).set(0.0)
            self._tenant_buckets = live_tenants
        elapsed = self.clock() - self._t0
        qw, lat = self._h_queue_wait, self._h_latency
        self.recorder.record(
            {
                "kind": "serve",
                "pump": "pipelined" if self.config.pipeline else "sync",
                "elapsed_s": elapsed,
                "queue_depth": stats.queue_depth,
                "batch_occupancy": occ,
                "admitted": stats.admitted,
                "completed": stats.completed,
                "failed": stats.failed,
                "steps_advanced": stats.steps_advanced,
                # path attribution (docs/OBSERVABILITY.md): the slice of
                # this round's steps run by bitplane-packed engines, so
                # `tpu-life stats` splits throughput by storage path
                "steps_advanced_packed": stats.steps_advanced_packed,
                # the stencil stamp (docs/RULES.md): live engines on the
                # banded-matmul counting path, and each key's resolved
                # path — the per-round record a tailing consumer (and
                # the fleet merge) attributes throughput with
                "matmul_keys": matmul_keys,
                "stencil_keys": {
                    _key_bucket(k): e.stencil
                    for k, e in self.scheduler.engines.items()
                    if getattr(e, "stencil", None) is not None
                },
                # the mesh stamp (docs/SERVING.md "Mega-board sessions"),
                # present only on workers with a configured slice —
                # records of mesh-less workers keep their prior shape
                **(
                    {"mesh_sessions": mesh_sessions}
                    if self.config.mesh_devices
                    else {}
                ),
                "sessions_done": self._completed,
                "sessions_per_sec": self._completed / elapsed
                if elapsed > 0
                else 0.0,
                # the overlap stamps: in-flight chunks after this round's
                # dispatches, and cumulative engine-idle wall seconds
                "pipeline_depth": depth,
                "device_idle_s": self._c_device_idle.value,
                # the governor stamps (docs/SERVING.md "Resource
                # governance"): in-place recoveries this round, and the
                # cumulative ladder counter
                "engine_recoveries": stats.engine_recoveries,
                # the durability stamps (present only with a spill dir):
                # sessions currently resumable from disk, and cumulative
                # wall seconds spent writing spills
                **(
                    {
                        "spilled_sessions": self._spill.spilled_count(),
                        "snapshot_s": self._snapshot_s_total,
                        "spill_errors": self._c_spill_errors.value,
                    }
                    if self._spill is not None
                    else {}
                ),
                # the stream stamps (docs/STREAMING.md), present only
                # once the stream tier has ever been touched — records of
                # never-streamed services keep their pre-stream shape
                **(
                    {
                        "stream_watchers": stream_watchers,
                        "stream_frames_total": self._stream_frames_seen,
                        "stream_frame_gaps_total": self._stream_gaps_seen,
                    }
                    if stream_watchers or self._stream_frames_seen
                    else {}
                ),
                # live distribution snapshots (null until first sample):
                # the per-round record carries its histograms' quantiles so
                # a tailing consumer sees latency drift round by round
                "queue_wait_p50": qw.quantile(0.5),
                "queue_wait_p95": qw.quantile(0.95),
                "queue_wait_p99": qw.quantile(0.99),
                "completion_p50": lat.quantile(0.5),
                "completion_p95": lat.quantile(0.95),
            }
        )
        # the series sample rides the retire tail, rate-limited to one
        # snapshot per series_every_s no matter how fast rounds spin;
        # disabled sampling is the single is-None check above this line
        if self._series is not None:
            now_mono = self.clock()
            if now_mono >= self._series_next:
                self._series_next = now_mono + self.config.series_every_s
                self._series.sample(self.registry)
        if self.config.prom_file:
            # live exposition: rewrite the snapshot every round (atomic
            # rename, so a mid-run scrape never reads a torn file) instead
            # of only at close — a Prometheus file scraper watching a
            # long-lived serve sees queue depth move, not a stale zero
            self._write_prom()

    def _write_prom(self) -> None:
        path = self.config.prom_file
        obs.ensure_parent(path)
        with ckpt_atomic_publish(Path(path)) as tmp:
            tmp.write_text(self.registry.prom_text())

    def drain_trace(self) -> dict:
        """Take (and clear) the buffered trace + flight events — the
        payload behind the gateway's ``GET /v1/debug/trace`` drain verb
        (docs/OBSERVABILITY.md "Distributed tracing").  Each call is an
        increment: a fleet supervisor scraping on its monitor tick
        assembles the whole timeline without ever re-reading an event.
        With no tracer configured the span list is empty but the flight
        ring (always on) still drains — a no-trace worker still
        contributes its control-plane decisions to a postmortem."""
        t = self._tracer
        payload = {
            "run_id": self.run_id,
            "pid": os.getpid(),
            "now": time.time(),
            "wall_t0": t.wall_t0 if t is not None else None,
            "dropped": t.dropped if t is not None else 0,
            "events": t.drain() if t is not None else [],
            "flight": obs.flight.drain(),
        }
        return payload

    def read_series(self, cursor: int = 0) -> dict:
        """Retained metric snapshots with ``seq >= cursor`` — the payload
        behind ``GET /v1/debug/series?cursor=`` (docs/OBSERVABILITY.md
        "Time series").  Unlike the trace drain this read is
        NON-destructive and repeatable: the scraper owns the cursor, so
        a replayed scrape (or a second scraper) sees the same snapshots;
        ``dropped`` counts what the bounded ring evicted past the cursor
        before this read.  A disabled ring answers an empty, well-shaped
        payload rather than a 404 — the scraper needs no config probe."""
        if self._series is None:
            payload = {
                "schema": obs.timeseries.SERIES_SCHEMA,
                "snapshots": [],
                "next_cursor": 0,
                "dropped": 0,
            }
        else:
            payload = self._series.read(cursor)
        payload.update(run_id=self.run_id, pid=os.getpid(), now=time.time())
        return payload

    def flush(self) -> None:
        """Wait out any still-in-flight device chunks without running a
        new round.  The drain tail calls this after ``idle()`` turns true:
        a chunk whose sessions were all cancelled mid-flight is otherwise
        left executing with nobody to collect it."""
        with self._lock:
            self.scheduler.flush_inflight()

    def release_idle_engines(self) -> int:
        """Free engines (device batch + compiled program) whose keys have
        no resident sessions — for quiet periods of a long-lived service;
        returning traffic for a released key costs one recompile."""
        with self._lock:
            # harvest the idle tail first: deltas on a deleted engine are
            # gone, and the counter must stay monotonic across releases
            idle_delta = self.scheduler.idle_seconds_delta()
            if idle_delta > 0:
                self._c_device_idle.inc(idle_delta)
            return self.scheduler.release_idle_engines()

    def close(self) -> None:
        """Flush telemetry and release held resources: the registry
        snapshot lands in the JSONL sink, the Prometheus snapshot in
        ``prom_file``, the trace file is written, in-flight chunks
        collected, idle engines freed."""
        self._watchdog_stop.set()
        with self._lock:
            self.scheduler.flush_inflight()
            self.recorder.close()
            if self.config.prom_file:
                self._write_prom()
                log.info("prometheus snapshot -> %s", self.config.prom_file)
            if self._tracer is not None:
                # the flight-recorder dump (docs/OBSERVABILITY.md): what
                # is still in the control-plane ring rides into the
                # written file as instant markers, so a solo gateway's
                # trace file is a self-contained postmortem capture
                t = self._tracer
                for ev in obs.flight.snapshot():
                    t._emit(
                        obs.flight.as_instant(
                            ev,
                            pid=os.getpid(),
                            ts=max(0.0, (ev["t"] - t.wall_t0) * 1e6),
                        )
                    )
                obs.stop_tracing(self._tracer)
                log.info(
                    "trace events -> %s (run_id=%s)", self._tracer.path, self.run_id
                )
                self._tracer = None
            self.scheduler.release_idle_engines()

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        elapsed = self.clock() - self._t0
        return {
            "run_id": self.run_id,
            "draining": self._draining,
            "wedged": self._wedged,
            "memory_budget_bytes": self._memory_budget or 0,
            "engine_recoveries": {
                labels["outcome"]: inst.value
                for labels, inst in self._c_recoveries.series()
            },
            "pump": "pipelined" if self.config.pipeline else "sync",
            "pipeline_depth": self._g_pipeline_depth.value,
            "device_idle_seconds": self._c_device_idle.value,
            "spilled_sessions": (
                self._spill.spilled_count() if self._spill is not None else 0
            ),
            "snapshot_seconds": self._snapshot_s_total,
            "spill_errors": self._c_spill_errors.value,
            "stream_watchers": int(self._g_stream_watchers.value),
            "stream_frames_total": int(self._c_stream_frames.value),
            "stream_frame_gaps_total": int(self._c_stream_gaps.value),
            "queue_wait_p50": self._h_queue_wait.quantile(0.5),
            "queue_wait_p95": self._h_queue_wait.quantile(0.95),
            "queue_wait_p99": self._h_queue_wait.quantile(0.99),
            "completion_p50": self._h_latency.quantile(0.5),
            "completion_p95": self._h_latency.quantile(0.95),
            "rejections": self._c_rejections.value,
            "sessions": len(self.store),
            "queued": self.store.count(SessionState.QUEUED),
            "running": self.store.count(SessionState.RUNNING),
            "done": self.store.count(SessionState.DONE),
            "failed": self.store.count(SessionState.FAILED),
            "cancelled": self.store.count(SessionState.CANCELLED),
            "rounds": self._rounds,
            "steps_advanced": self._steps_total,
            "steps_advanced_packed": self._steps_packed_total,
            # the per-key stencil stamp (docs/RULES.md): which counting
            # path each live CompileKey compiled, and the matmul count
            "matmul_keys": int(self._g_matmul_keys.value),
            "stencil_keys": {
                _key_bucket(k): e.stencil
                for k, e in self.scheduler.engines.items()
                if getattr(e, "stencil", None) is not None
            },
            # the mesh tier (docs/SERVING.md "Mega-board sessions"):
            # sessions currently sharded over the reserved slice
            "mesh_sessions": int(self._g_mesh_sessions.value),
            # tenant QoS (docs/SERVING.md "Tenant QoS"): live sessions
            # and typed sheds per tenant — {} on policy-less services,
            # so the stats shape only grows when a policy exists
            **(
                {
                    "tenants": self.store.live_by_tenant(),
                    "tenant_sheds": {
                        f"{labels['tenant']}:{labels['reason']}": int(
                            inst.value
                        )
                        for labels, inst in self._c_tenant_shed.series()
                    },
                }
                if self._qos is not None
                else {}
            ),
            "elapsed_s": elapsed,
            "sessions_per_sec": self._completed / elapsed if elapsed > 0 else 0.0,
            "batch_occupancy_mean": self._occupancy_sum / self._rounds
            if self._rounds
            else 0.0,
            "compile_counts": {
                repr(k): v for k, v in self.scheduler.compile_counts().items()
            },
        }


def _key_bucket(key: CompileKey) -> str:
    """The bounded label a CompileKey becomes in the registry:
    ``rule:HxW:backend`` — small closed sets by construction."""
    h, w = key.shape
    return f"{key.rule.name}:{h}x{w}:{key.backend}"
