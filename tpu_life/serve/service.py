"""The public serving API: ``submit / poll / cancel / drain``.

:class:`SimulationService` is the in-process serving core — the piece of
the repo whose shape is an inference stack rather than a batch job.  A
network front-end would be a thin shell over exactly these four verbs;
the CLI's ``serve`` / ``submit`` modes are the first such shell.

Execution is cooperative: ``pump()`` runs one scheduling round (expire
deadlines -> admit from the queue -> one batched device chunk per engine
-> retire finished sessions), ``drain()`` pumps until idle.  Cooperative
beats background threads here for the same reason the driver is a
synchronous loop: every test and every caller sees a deterministic
interleaving, and the host-sync chunk boundary is already the natural
scheduling quantum (sessions join and leave the batch only there).

Observability rides the existing runtime seams: every pump emits a
``MetricsRecorder`` record (queue depth, batch occupancy, sessions/sec),
and ``drain`` runs under ``runtime.profiling.maybe_profile`` so a serve
trace lands in the same XProf tooling as a batch run.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from tpu_life.models.rules import Rule, get_rule
from tpu_life.runtime.metrics import MetricsRecorder, log
from tpu_life.runtime.profiling import maybe_profile
from tpu_life.serve.engine import CompileKey, compile_key_for
from tpu_life.serve.scheduler import RoundStats, Scheduler
from tpu_life.serve.sessions import (
    SessionState,
    SessionStore,
    SessionView,
    TERMINAL,
)


@dataclass
class ServeConfig:
    capacity: int = 8  # batch slots per compile key
    chunk_steps: int = 16  # device steps per scheduling round
    max_queue: int = 64  # bounded admission queue (backpressure)
    backend: str = "jax"  # engine executor: jax | numpy | sharded | pallas | ...
    default_timeout_s: float | None = None  # per-request deadline default
    metrics: bool = False  # record per-pump serve metrics
    metrics_file: str | None = None  # JSONL sink (implies metrics)
    profile: str | None = None  # jax.profiler trace dir for drain()


class SimulationService:
    def __init__(self, config: ServeConfig | None = None, *, clock=time.monotonic):
        self.config = config or ServeConfig()
        if self.config.max_queue < 1:
            # a zero-length queue can never admit anything: every submit
            # would bounce and a retry-on-QueueFull client would spin
            raise ValueError(
                f"max_queue must be >= 1, got {self.config.max_queue}"
            )
        # fail at construction, not at the first admission's lazy engine
        # build (EngineBase re-checks, but by then sessions are queued)
        if self.config.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.config.capacity}")
        if self.config.chunk_steps < 1:
            raise ValueError(
                f"chunk_steps must be >= 1, got {self.config.chunk_steps}"
            )
        self.clock = clock
        self.store = SessionStore()
        self.scheduler = Scheduler(
            capacity=self.config.capacity,
            chunk_steps=self.config.chunk_steps,
            max_queue=self.config.max_queue,
            clock=clock,
        )
        self.recorder = MetricsRecorder(
            0,
            self.config.metrics,
            sink=self.config.metrics_file,
        )
        self._t0 = clock()
        self._completed = 0
        self._rounds = 0
        self._occupancy_sum = 0.0  # for mean batch occupancy in stats()

    # -- the four verbs ----------------------------------------------------
    def submit(
        self,
        board: np.ndarray,
        rule: Rule | str,
        steps: int,
        *,
        timeout_s: float | None = None,
        fault_at: int = 0,
    ) -> str:
        """Admit one simulation request; returns its session id.

        Validates exactly what the driver validates (2-D int8 board, every
        state within the rule's range, non-negative budget) and raises
        :class:`QueueFull` when the bounded queue is at capacity — the
        request is rejected before anything is stored, so backpressure
        bounds memory, not just slots.
        """
        if isinstance(rule, str):
            rule = get_rule(rule)
        # validate BEFORE the int8 cast: a wider-dtype caller array with
        # state 256 would wrap to 0 and sail through a post-cast check —
        # simulated junk, not a rejection
        board = np.asarray(board)
        if board.ndim != 2:
            raise ValueError(f"board must be 2-D, got shape {board.shape}")
        max_state = int(board.max(initial=0))
        if max_state >= rule.states:
            raise ValueError(
                f"board contains state {max_state} but rule {rule.name!r} "
                f"has only {rule.states} states (0..{rule.states - 1})"
            )
        min_state = int(board.min(initial=0))
        if min_state < 0:
            # the driver's file codec cannot produce negatives, but a
            # library caller's array can — reject rather than simulate junk
            raise ValueError(
                f"board contains negative state {min_state}; states are "
                f"0..{rule.states - 1}"
            )
        board = board.astype(np.int8)
        if steps < 0:
            raise ValueError(f"steps must be >= 0, got {steps}")
        # backpressure check BEFORE the session exists anywhere
        self.scheduler.ensure_admission()
        now = self.clock()
        if timeout_s is None:
            timeout_s = self.config.default_timeout_s
        s = self.store.create(
            board=board.copy(),
            rule=rule,
            steps=steps,
            submitted_at=now,
            deadline=None if timeout_s is None else now + timeout_s,
            fault_at=fault_at,
        )
        if steps == 0:
            # nothing to run: complete at admission, never costs a slot
            s.finish(board.copy())
            self._completed += 1
        else:
            self.scheduler.enqueue(s)
        log.debug("serve: submitted %s (%s, %d steps)", s.sid, rule.name, steps)
        return s.sid

    def poll(self, sid: str) -> SessionView:
        return self.store.view(sid)

    def result(self, sid: str) -> np.ndarray:
        return self.store.result(sid)

    def cancel(self, sid: str) -> bool:
        """Stop a session wherever it is; True if this call stopped it.

        Cancelling a RUNNING session frees its batch slot at the next
        round boundary semantics: the slot is released immediately, the
        engine's freeze mask stops stepping it, and the partial board is
        discarded (``steps_done`` records how far it got).
        """
        s = self.store.get(sid)
        if s.state in TERMINAL:
            return False
        if s.state is SessionState.QUEUED:
            self.scheduler.remove_queued(s)
        else:
            self.scheduler.evict_running(s)
        s.cancel()
        return True

    def drain(self, max_rounds: int | None = None) -> int:
        """Pump until every admitted session reaches a terminal state;
        returns the number of rounds run.  ``max_rounds`` bounds a stuck
        drain (it raises rather than spinning forever)."""
        rounds = 0
        with maybe_profile(self.config.profile):
            while not self.scheduler.idle():
                self.pump()
                rounds += 1
                if max_rounds is not None and rounds >= max_rounds:
                    if not self.scheduler.idle():
                        raise RuntimeError(
                            f"drain did not converge in {max_rounds} rounds "
                            f"({len(self.scheduler.queue)} queued)"
                        )
                    break
        return rounds

    # -- the scheduling quantum -------------------------------------------
    def pump(self) -> RoundStats:
        """One scheduling round; the only place device work happens."""
        cfg = self.config

        def keyer(s) -> CompileKey:
            return compile_key_for(s.rule, s.board, cfg.backend)

        stats = self.scheduler.round(keyer)
        self._completed += stats.completed
        self._rounds += 1
        occ = stats.occupancy / stats.slots if stats.slots else 0.0
        self._occupancy_sum += occ
        elapsed = self.clock() - self._t0
        self.recorder.record(
            {
                "kind": "serve",
                "elapsed_s": elapsed,
                "queue_depth": stats.queue_depth,
                "batch_occupancy": occ,
                "admitted": stats.admitted,
                "completed": stats.completed,
                "failed": stats.failed,
                "steps_advanced": stats.steps_advanced,
                "sessions_done": self._completed,
                "sessions_per_sec": self._completed / elapsed
                if elapsed > 0
                else 0.0,
            }
        )
        return stats

    def release_idle_engines(self) -> int:
        """Free engines (device batch + compiled program) whose keys have
        no resident sessions — for quiet periods of a long-lived service;
        returning traffic for a released key costs one recompile."""
        return self.scheduler.release_idle_engines()

    def close(self) -> None:
        """Release held resources: the metrics sink handle and every idle
        engine.  The service remains usable afterwards (the sink reopens
        on the next record)."""
        self.recorder.close()
        self.scheduler.release_idle_engines()

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        elapsed = self.clock() - self._t0
        return {
            "sessions": len(self.store),
            "queued": self.store.count(SessionState.QUEUED),
            "running": self.store.count(SessionState.RUNNING),
            "done": self.store.count(SessionState.DONE),
            "failed": self.store.count(SessionState.FAILED),
            "cancelled": self.store.count(SessionState.CANCELLED),
            "rounds": self._rounds,
            "elapsed_s": elapsed,
            "sessions_per_sec": self._completed / elapsed if elapsed > 0 else 0.0,
            "batch_occupancy_mean": self._occupancy_sum / self._rounds
            if self._rounds
            else 0.0,
            "compile_counts": {
                repr(k): v for k, v in self.scheduler.compile_counts().items()
            },
        }
