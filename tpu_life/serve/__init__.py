"""tpu_life.serve: the multi-tenant batched simulation service.

The first piece of the repo shaped like an inference stack rather than a
batch job (ROADMAP north star: "serving heavy traffic").  Many concurrent
sessions — (board, rule, step budget) each — are packed into fixed-
capacity batches by compatible compile key and advanced by one compiled
vmapped step per chunk, with continuous batching (sessions join and leave
between host-sync chunks, zero recompilation), a bounded admission queue
(typed backpressure), per-request deadlines, and per-slot failure
isolation.

Quick start::

    from tpu_life.serve import ServeConfig, SimulationService

    svc = SimulationService(ServeConfig(capacity=8, backend="jax"))
    sid = svc.submit(board, "conway", steps=100)
    svc.drain()
    final = svc.result(sid)

See docs/SERVING.md for the architecture and the batching/compile-key
rules, and ``tpu-life serve`` / ``tpu-life submit`` for the CLI front-end.
"""

from tpu_life.serve.engine import CompileKey, compile_key_for, make_engine
from tpu_life.serve.errors import (
    Draining,
    InsufficientMemory,
    QueueFull,
    ServeError,
    SessionFailed,
    SessionTimeout,
    UnknownSession,
)
from tpu_life.serve.scheduler import RoundStats, Scheduler
from tpu_life.serve.service import ServeConfig, SimulationService
from tpu_life.serve.sessions import Session, SessionState, SessionStore, SessionView

__all__ = [
    "CompileKey",
    "Draining",
    "InsufficientMemory",
    "QueueFull",
    "RoundStats",
    "Scheduler",
    "ServeConfig",
    "ServeError",
    "Session",
    "SessionFailed",
    "SessionState",
    "SessionStore",
    "SessionTimeout",
    "SessionView",
    "SimulationService",
    "UnknownSession",
    "compile_key_for",
    "make_engine",
]
