"""Admission and slot scheduling: the queue/engine split.

The MPMD-serving lesson (PAPERS.md, arXiv:2412.14374) is to separate
request *ingestion* from device *stepping*: requests land in a bounded
FIFO queue, a scheduling round moves compatible sessions into batch
slots, and the engines advance whatever is resident.  Policies:

- **Backpressure**: the queue is bounded (``max_queue``); an enqueue past
  capacity raises :class:`~tpu_life.serve.errors.QueueFull` *before* the
  session is stored, so a misbehaving client cannot grow memory.
- **Admission**: sessions are grouped by :class:`CompileKey`; each key
  lazily gets one engine with ``capacity`` slots.  Within a key the order
  is strict FIFO; across keys the queue is scanned in submission order so
  a full engine for one key never head-of-line-blocks another key's
  sessions (per-key FIFO, globally work-conserving).
- **Deadline-aware eviction**: a session past its deadline is failed with
  :class:`SessionTimeout` wherever it is — dropped from the queue, or
  evicted from its running slot so the batch's capacity goes back to
  tenants that can still meet theirs.
- **Per-slot failure isolation**: a failing session (the ``fault_at``
  drill, or any RECOVERABLE error surfacing during its slot operations —
  ``runtime.recovery`` semantics) marks only that session FAILED and
  frees its slot; the rest of the batch keeps stepping.

Two round shapes share these policies:

- :meth:`Scheduler.round` — the classic host-synchronous quantum
  (admit -> advance -> retire, each engine's chunk awaited in place).
  This is the oracle the pipelined pump is bit-compared against.
- :meth:`Scheduler.round_begin` / :meth:`Scheduler.round_end` — the
  pipelined round the service drives in three phases (docs/SERVING.md):
  *begin* (locked) expires, admits, and async-dispatches one chunk per
  engine in rotated key order — so a mixed-rule population round-robins
  its compiled steps and a slow or faulted key never head-of-line-blocks
  another key's pipeline; *settle* (run by the service OUTSIDE its lock)
  lets device chunks and host-engine compute finish; *end* (locked)
  retires the PREVIOUS dispatch's finishers from the engines' double
  buffers, re-admits into the freed slots, and late-dispatches engines
  that were empty at begin.  Retirement lags dispatch by one round by
  construction — that lag is the overlap.  Per-key in-flight tracking
  (``pending`` / each engine's own in-flight chunk) keeps the keys'
  pipelines independent.  Verb-triggered slot releases that land while
  an engine is settling outside the lock are parked in ``deferred`` and
  applied at the next begin — verbs never mutate an engine mid-compute.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from tpu_life import obs
from tpu_life.runtime import recovery
from tpu_life.runtime.metrics import log
from tpu_life.serve.engine import (
    CompileKey,
    EngineBase,
    make_engine,
    make_host_engine,
)
from tpu_life.serve.errors import QueueFull, SessionTimeout
from tpu_life.serve.sessions import Session, SessionState


def _slot_attrs(slots: dict) -> dict:
    """Per-slot trace attributes for a dispatch/step span — WHICH
    sessions (and which distributed traces) this device chunk advanced.
    Guarded by the one-global-check discipline: with no active tracer
    this is a single ``None`` test and allocates nothing."""
    if not obs.tracing():
        return {}
    return {
        "sids": [s.sid for s in slots.values()],
        "trace_ids": sorted(
            {s.trace_id for s in slots.values() if s.trace_id is not None}
        ),
    }


@dataclass
class RoundStats:
    """What one scheduling round did — the metrics payload."""

    admitted: int = 0
    completed: int = 0
    failed: int = 0
    evicted: int = 0
    # in-place engine recoveries this round (docs/SERVING.md "Resource
    # governance"): a chunk fault that was masked instead of failing a key
    engine_recoveries: int = 0
    steps_advanced: int = 0
    # the slice of steps_advanced run by bitplane-packed engines — the
    # per-round attribution `tpu-life stats` splits throughput on
    steps_advanced_packed: int = 0
    queue_depth: int = 0
    occupancy: int = 0  # occupied slots across engines after the round
    slots: int = 0  # total allocated slots across engines


@dataclass
class Scheduler:
    capacity: int = 8  # batch slots per engine (per compile key)
    chunk_steps: int = 16  # device steps per host-sync scheduling round
    max_queue: int = 64  # bounded admission queue (backpressure)
    # the stochastic tier's bitplane knob (ServeConfig.mc_packed): ising
    # batches run on the packed device engine unless pinned off
    mc_packed: bool = True
    # tenant QoS (docs/SERVING.md "Tenant QoS"): when set (duck-typed —
    # anything with ``admission_order(sessions, cursor)``), the admit
    # scan orders the queue by deficit-round-robin over tenants instead
    # of plain FIFO, so one hog tenant cannot starve the rest of batch
    # slots.  None keeps the exact FIFO scan, byte for byte.
    qos: object | None = None
    # in-place recovery budget (docs/SERVING.md "Resource governance"):
    # how many chunk-level RECOVERABLE faults per CompileKey are masked
    # by rebuild-and-replay before falling back to the typed failure.
    # 0 restores the pure failure-isolating behavior.
    engine_max_restarts: int = 3
    clock: object = time.monotonic

    queue: deque = field(default_factory=deque)
    engines: dict = field(default_factory=dict)  # CompileKey -> EngineBase
    running: dict = field(default_factory=dict)  # CompileKey -> {slot: Session}
    # telemetry observer (duck-typed; the service implements it): notified
    # on admission (with the measured queue wait) and on every terminal
    # transition the scheduler performs (with the submit-to-finish latency)
    observer: object | None = None
    # pipelined-round state: sessions that finished inside an already-
    # dispatched chunk, awaiting retirement once that chunk settles
    # (CompileKey -> [(slot, Session)]) ...
    pending: dict = field(default_factory=dict)
    # ... the finishers of the round currently being built ...
    _fresh: dict = field(default_factory=dict)
    # ... slot releases parked while their engine settles outside the
    # service lock (a cancel must not race an engine mid-compute), and
    # the key-rotation cursor for round-robin dispatch order
    deferred: list = field(default_factory=list)
    _rotation: int = 0
    # the in-place recovery ladder's per-key state (docs/SERVING.md
    # "Resource governance"): recoveries consumed, a halved chunk size
    # (the first OOM rung), keys demoted to the host executor (the
    # second), and the degraded_reason stamped onto their sessions
    restarts: dict = field(default_factory=dict)
    chunk_override: dict = field(default_factory=dict)
    demoted: set = field(default_factory=set)
    degraded: dict = field(default_factory=dict)

    # -- ingestion ---------------------------------------------------------
    def ensure_admission(self) -> None:
        """Raise :class:`QueueFull` when the bounded queue is at capacity.

        Exposed separately so the service can reject a submission *before*
        constructing and storing the session — backpressure that bounds
        memory, not just slots.
        """
        if len(self.queue) >= self.max_queue:
            raise QueueFull(
                f"admission queue full ({self.max_queue} sessions queued); "
                f"retry after the batch drains"
            )

    def enqueue(self, session: Session) -> None:
        self.ensure_admission()
        self.queue.append(session)

    def remove_queued(self, session: Session) -> bool:
        try:
            self.queue.remove(session)
            return True
        except ValueError:
            return False

    def evict_running(self, session: Session) -> bool:
        """Free a RUNNING session's slot (cancel / deadline); the caller
        sets the session's terminal state.  While the slot's engine is
        settling outside the service lock the release is parked in
        ``deferred`` (applied at the next round's begin) — touching an
        engine mid-compute from a verb thread would race the pump."""
        for key, slots in self.running.items():
            for slot, s in list(slots.items()):
                if s is session:
                    del slots[slot]
                    engine = self.engines[key]
                    if engine.busy:
                        self.deferred.append((key, slot))
                    else:
                        engine.release(slot)
                    return True
        return False

    def _process_deferred(self) -> None:
        for key, slot in self.deferred:
            engine = self.engines.get(key)
            if engine is not None:
                engine.release(slot)
        self.deferred.clear()

    # -- mid-run steering (docs/STREAMING.md "Edits") -----------------------
    def _load_budget(self, s: Session) -> int:
        """The step budget a slot load may carry: the session's remaining
        steps, capped at the next scheduled edit's boundary so the slot
        FREEZES exactly there (``remaining == 0`` is the engines' own
        freeze mask) whatever the chunk cadence — the seam a resumed
        edit log re-applies through at bit-exact positions."""
        budget = s.steps_remaining
        if s.scheduled_edits:
            abs_done = s.start_step + s.steps_done
            budget = min(budget, max(0, s.scheduled_edits[0][0] - abs_done))
        return budget

    def apply_edits(self, stats: RoundStats | None = None) -> int:
        """Drain verb-queued cell edits (``pending_edits``) and due
        scheduled edits into their sessions' slots, between chunks.

        Runs at the top of both round shapes, before any dispatch: for
        each key with an edit due, the in-flight chunk (if any) is
        collected — an edit is a sync point for ITS key only; other
        keys' pipelines never notice — then the slot's materialized
        board is peeked, mutated, and reloaded at the same absolute
        position (the freeze-mask seam: collect -> peek -> mutate ->
        load).  Scheduled edits log at their ORIGINAL recorded step,
        verb edits at the current materialized step; both land in
        ``s.edits``, the log the spill manifest persists and the replay
        oracle re-executes.  Returns how many log entries were applied.
        """
        stats = stats if stats is not None else RoundStats()
        applied = 0
        for key in list(self.running):
            slots = self.running.get(key)
            engine = self.engines.get(key)
            if not slots or engine is None:
                continue
            due = [
                (slot, s)
                for slot, s in list(slots.items())
                if s.pending_edits
                or (
                    s.scheduled_edits
                    and s.scheduled_edits[0][0] <= s.start_step + s.steps_done
                )
            ]
            if not due:
                continue
            if engine.inflight:
                try:
                    engine.collect_chunk()
                except recovery.RECOVERABLE as e:
                    # the chunk under the edit died: recover the key in
                    # place; the edits stay pending and apply next round
                    # against the rebuilt engine's replayed boards
                    self.recover_engine(key, e, stats)
                    continue
            for slot, s in due:
                if slots.get(slot) is not s:
                    continue  # evicted/cancelled while collecting
                try:
                    board, lag = engine.peek_slot(slot)
                except recovery.RECOVERABLE as e:
                    self.recover_engine(key, e, stats)
                    break
                # lag is 0 after the collect above; rewind defensively so
                # the log step always names a materialized board
                s.steps_done -= lag
                board = np.array(board, copy=True)
                abs_done = s.start_step + s.steps_done
                entries = []
                while (
                    s.scheduled_edits
                    and s.scheduled_edits[0][0] <= abs_done
                ):
                    entries.append(s.scheduled_edits.pop(0))
                entries.extend((abs_done, cells) for cells in s.pending_edits)
                s.pending_edits.clear()
                hook = getattr(self.observer, "session_edited", None)
                for step, cells in entries:
                    for r, c, v in cells:
                        board[r, c] = v
                    s.edits.append((step, cells))
                    applied += 1
                    if hook is not None:
                        hook(s, step, cells)
                try:
                    engine.load(
                        slot,
                        board,
                        self._load_budget(s),
                        seed=s.seed,
                        temperature=s.temperature,
                        start_step=abs_done,
                    )
                except recovery.RECOVERABLE as e:
                    del slots[slot]
                    engine.release(slot)
                    s.fail(f"edit reload failed: {type(e).__name__}: {e}")
                    self._notify_finished(s)
                    stats.failed += 1
        return applied

    # -- one scheduling round ---------------------------------------------
    def round(self, keyer) -> RoundStats:
        """Expire deadlines, admit from the queue, advance every engine one
        chunk, retire finished slots.  ``keyer(session) -> CompileKey``.
        """
        stats = RoundStats()
        self.apply_edits(stats)
        now = self.clock()
        with obs.span("serve.admit"):
            self._expire(now, stats)
            self._admit(keyer, stats)
        # occupancy is sampled when the batch STEPS (post-admit, pre-
        # advance): sampling after retirement would report an always-empty
        # batch whenever sessions finish within one round
        stats.occupancy = sum(e.occupancy() for e in self.engines.values())
        stats.slots = sum(e.capacity for e in self.engines.values())
        self._advance(stats)
        stats.queue_depth = len(self.queue)
        return stats

    def _expire(self, now: float, stats: RoundStats) -> None:
        # queued sessions past deadline: drop before they ever cost a slot
        for s in [s for s in self.queue if s.deadline is not None and now >= s.deadline]:
            self.queue.remove(s)
            e = SessionTimeout(
                f"deadline expired after {s.steps_done} steps (queued)"
            )
            s.fail(f"{type(e).__name__}: {e}")
            self._notify_finished(s)
            stats.failed += 1
            log.info("serve: session %s timed out in queue", s.sid)
        # running sessions past deadline: evict — their slot goes back to
        # tenants that can still meet their deadlines
        for key, slots in self.running.items():
            for slot, s in list(slots.items()):
                if s.steps_remaining == 0:
                    # fully computed, awaiting retirement (the pipelined
                    # pump retires one round after dispatch): under the
                    # sync pump this session retired DONE in its final
                    # round, so failing it here would make the overlap
                    # change an outcome — the one thing it must never do
                    continue
                if s.deadline is not None and now >= s.deadline:
                    del slots[slot]
                    self.engines[key].release(slot)
                    e = SessionTimeout(
                        f"deadline expired after {s.steps_done} steps (running)"
                    )
                    s.fail(f"{type(e).__name__}: {e}")
                    self._notify_finished(s)
                    stats.failed += 1
                    stats.evicted += 1
                    log.info("serve: session %s evicted (deadline)", s.sid)

    def _admit_order(self) -> list:
        """Drain the queue into this round's admission scan order: FIFO
        without a QoS policy; deficit-round-robin over tenants with one
        (per-tenant FIFO preserved — only the interleave changes).  The
        rotation cursor reuses the dispatch rotation counter so tenant
        ties don't always break toward the same name."""
        order = list(self.queue)
        self.queue.clear()
        if self.qos is not None and order:
            order = self.qos.admission_order(order, cursor=self._rotation)
        return order

    def _admit(self, keyer, stats: RoundStats) -> None:
        deferred = []
        for s in self._admit_order():
            key = keyer(s)
            engine = self.engines.get(key)
            if engine is None:
                try:
                    engine = self._build_engine(key)
                except recovery.RECOVERABLE as e:
                    # an engine build that OOMs (device_put of the batch,
                    # a first allocation) must fail only this key's
                    # admit, typed — never escape into the pump.  Later
                    # queued sessions of the same key each retry (and
                    # fail) their own admit.
                    s.fail(f"engine build failed: {type(e).__name__}: {e}")
                    self._notify_finished(s)
                    stats.failed += 1
                    log.warning(
                        "serve: engine build for %r failed at admit: %s",
                        key, e,
                    )
                    continue
                self.engines[key] = engine
                self.running[key] = {}
            slot = engine.acquire()
            if slot is None:
                # this key's batch is full: defer, keep scanning.  Later
                # sessions of the SAME key also find it full and defer in
                # order (per-key FIFO holds); other keys stay unblocked.
                deferred.append(s)
                continue
            try:
                loader = getattr(s, "mesh_resume", None)
                if loader is not None and hasattr(engine, "load_tiles"):
                    # shard-wise mega-board resume (docs/SERVING.md
                    # "Mega-board sessions"): the session carries a tile
                    # block loader instead of a board — each destination
                    # shard pulls its own rectangle at load, possibly
                    # onto a different mesh shape than the one that
                    # spilled (arXiv 2112.01075).  Consumed once: a
                    # later re-admit (engine recovery) reloads from the
                    # engine's own salvaged state like any session.
                    engine.load_tiles(
                        slot,
                        loader,
                        self._load_budget(s),
                        start_step=s.start_step + s.steps_done,
                    )
                    s.mesh_resume = None
                else:
                    # seed/temperature are the stochastic per-slot state
                    # (validated at submit); deterministic engines ignore
                    # them.  start_step re-enters the counter-based PRNG
                    # stream at the session's absolute position — the
                    # resumed-after-failover case (start_step > 0) is
                    # bit-exact by construction
                    engine.load(
                        slot,
                        s.board,
                        self._load_budget(s),
                        seed=s.seed,
                        temperature=s.temperature,
                        start_step=s.start_step + s.steps_done,
                    )
            except recovery.RECOVERABLE as e:
                engine.release(slot)
                s.fail(f"load failed: {e}")
                self._notify_finished(s)
                stats.failed += 1
                continue
            s.state = SessionState.RUNNING
            s.slot = slot
            # the path stamp (docs/OBSERVABILITY.md): which storage layout
            # steps this session — echoed in views and round attribution
            s.packed = engine.packed
            s.lanes = engine.lanes
            # a key degraded by the OOM ladder stamps every later tenant
            # too: the operator sees WHICH sessions ran on the fallback
            reason = self.degraded.get(key)
            if reason is not None:
                s.degraded_reason = reason
            s.admitted_at = self.clock()
            if self.observer is not None:
                self.observer.session_admitted(
                    s, max(0.0, s.admitted_at - s.submitted_at)
                )
            self.running[key][slot] = s
            stats.admitted += 1
        self.queue.extend(deferred)

    def _fault_drill(self, engine: EngineBase, slots: dict, stats: RoundStats) -> None:
        # the fault-injection drill fires where a real per-slot device
        # failure would: before the chunk that crosses fault_at.  Only
        # the faulty tenant dies; its slot frees, the batch continues.
        for slot, s in list(slots.items()):
            to_run = min(engine.chunk_steps, s.steps_remaining)
            if not (s.fault_at and s.steps_done < s.fault_at <= s.steps_done + to_run):
                continue
            e = recovery.InjectedFault(
                f"injected per-slot device failure crossing step {s.fault_at}"
            )
            assert isinstance(e, recovery.RECOVERABLE)
            del slots[slot]
            engine.release(slot)
            s.fail(f"{type(e).__name__}: {e}")
            self._notify_finished(s)
            stats.failed += 1
            log.info("serve: session %s failed in slot %d: %s", s.sid, slot, e)

    def _build_engine(self, key) -> EngineBase:
        """The key's engine, honoring the recovery ladder's per-key state:
        a halved chunk after the first OOM, the host executor after the
        second — so a rebuilt (or re-minted, after release_idle_engines)
        engine for a degraded key stays degraded instead of re-OOMing."""
        chunk = self.chunk_override.get(key, self.chunk_steps)
        if key in self.demoted:
            return make_host_engine(key, self.capacity, chunk)
        return make_engine(key, self.capacity, chunk, mc_packed=self.mc_packed)

    def _notify_recovery(self, key, outcome: str) -> None:
        hook = getattr(self.observer, "engine_recovered", None)
        if hook is not None:
            hook(key, outcome)

    def recover_engine(self, key, exc, stats: RoundStats | None = None) -> bool:
        """In-place engine recovery after a chunk-level RECOVERABLE fault
        (docs/SERVING.md "Resource governance"): instead of failing the
        key's tenants typed, rebuild the engine and replay.

        Every resident session's newest *materialized* state is salvaged
        (``engine.salvage_slot``: the double buffer plus the in-flight /
        lost chunk's lag), its bookkeeping rewound by the lag, and the
        session reloaded into a fresh engine at the exact absolute
        position (``start_step + steps_done`` — the counter-based MC
        streams re-enter bit-identically, deterministic rules are pure
        functions of the board, and chunk invariance is already proven).
        A session whose compute is both finished AND materialized
        retires DONE right here.  A device OOM takes the **fallback
        ladder**: the first OOM halves the key's chunk (smaller scan
        footprint, same trajectory), a second demotes the key to the
        bit-identical host executor; both stamp ``degraded_reason`` on
        the key's sessions.  ``engine_max_restarts`` bounds recoveries
        per key — past it (or with the budget set to 0) the fault falls
        back to today's typed failure.  Returns True when the key was
        recovered in place."""
        stats = stats if stats is not None else RoundStats()
        error = f"{type(exc).__name__}: {exc}"
        engine = self.engines.get(key)
        slots = self.running.get(key)
        if engine is None or slots is None:
            return False
        used = self.restarts.get(key, 0) + 1
        self.restarts[key] = used
        if used > self.engine_max_restarts:
            self._notify_recovery(key, "budget_exhausted")
            self.fail_engine_sessions(key, error, stats)
            return False
        outcome = "replayed"
        if recovery.is_oom(exc) and key not in self.demoted:
            if key in self.chunk_override:
                # the halved chunk still OOMed: demote to the host twin —
                # sessions finish (slower) instead of failing typed
                self.demoted.add(key)
                outcome = "oom_host_demoted"
            else:
                self.chunk_override[key] = max(1, engine.chunk_steps // 2)
                outcome = "oom_halved_chunk"
            self.degraded[key] = outcome
        # salvage each resident session's newest trustworthy state; a
        # slot whose board cannot materialize (poisoned device buffer)
        # is genuinely lost and fails typed like before
        salvaged: list = []
        lost = 0
        for slot, s in list(slots.items()):
            del slots[slot]
            try:
                board, lag = engine.salvage_slot(slot)
            except recovery.RECOVERABLE as e2:
                s.fail(
                    f"salvage failed: {error} "
                    f"(then {type(e2).__name__}: {e2})"
                )
                self._notify_finished(s)
                stats.failed += 1
                lost += 1
                continue
            salvaged.append((s, board, lag))
        # condemn the old engine with its per-key transient state; parked
        # releases for this key are for already-evicted sessions — moot
        # against a fresh engine's clean slot pool
        self.pending.pop(key, None)
        self._fresh.pop(key, None)
        self.deferred = [(k, sl) for (k, sl) in self.deferred if k != key]
        try:
            new_engine = self._build_engine(key)
        except recovery.RECOVERABLE as e2:
            # the rebuild itself failed — e.g. the replacement batch
            # allocation OOMs while the condemned engine's buffers are
            # still alive.  The recovery path must NEVER let that escape
            # into the pump (it would kill the worker the governor
            # exists to keep alive): the salvaged sessions fall back to
            # the typed failure, the old engine stays registered for
            # future admissions (its slots are all free), and its lost
            # accounting is cleared like any typed-failure path.
            for s, _board, _lag in salvaged:
                s.fail(
                    f"recovery rebuild failed: {error} "
                    f"(then {type(e2).__name__}: {e2})"
                )
                self._notify_finished(s)
                stats.failed += 1
            engine.clear_lost()
            self._notify_recovery(key, "rebuild_failed")
            log.error(
                "serve: engine %r recovery REBUILD failed (%s after %s); "
                "%d session(s) failed typed",
                key, e2, error, len(salvaged),
            )
            return False
        self.engines[key] = new_engine
        reason = self.degraded.get(key)
        reloaded = retired = 0
        for s, board, lag in salvaged:
            # rewind to the materialized step: the lag steps were
            # accounted at dispatch but never materialized — the rebuilt
            # engine re-runs exactly them
            s.steps_done -= lag
            if reason is not None:
                s.degraded_reason = reason
            if s.steps_remaining == 0:
                # finished AND materialized (a pending finisher with zero
                # lag): its board is final — retire it DONE, the outcome
                # the sync pump already settled a round earlier
                s.finish(board)
                self._notify_finished(s)
                stats.completed += 1
                retired += 1
                continue
            slot = new_engine.acquire()
            try:
                new_engine.load(
                    slot,
                    board,
                    self._load_budget(s),
                    seed=s.seed,
                    temperature=s.temperature,
                    start_step=s.start_step + s.steps_done,
                )
            except recovery.RECOVERABLE as e2:
                new_engine.release(slot)
                s.fail(f"recovery reload failed: {type(e2).__name__}: {e2}")
                self._notify_finished(s)
                stats.failed += 1
                continue
            s.slot = slot
            s.packed = new_engine.packed
            s.lanes = new_engine.lanes
            slots[slot] = s
            reloaded += 1
        stats.engine_recoveries += 1
        self._notify_recovery(key, outcome)
        log.warning(
            "serve: engine %r recovered in place (%s, attempt %d/%d): "
            "%d session(s) replaying, %d retired, %d unsalvageable — %s",
            key, outcome, used, self.engine_max_restarts,
            reloaded, retired, lost, error,
        )
        return True

    def fail_engine_sessions(
        self, key, error: str, stats: RoundStats | None = None
    ) -> int:
        """Fail the resident sessions of ONE engine after a chunk-level
        RECOVERABLE fault (dispatch or collect raised) — with one
        carve-out: sessions whose compute ALREADY finished in an earlier
        chunk (this key's ``pending`` finishers, merely awaiting the
        one-round retirement lag) are RETIRED, not failed.  The sync
        pump retired them DONE a round ago, and the overlap must never
        change an outcome; their boards come from chunks that predate
        the fault, so collecting the engine's healthy in-flight chunk
        (if any) materializes them.  If that collect ALSO faults, their
        boards are genuinely unknowable and they fail with the rest.
        ``_fresh`` finishers stay failed: their chunk IS the one that
        died, so their final steps never materialized.  Every other key
        keeps stepping untouched — the batch-level analogue of the
        per-slot ``fault_at`` isolation: a device fault costs one key's
        tenants, never the pump and never a completed result."""
        stats = stats if stats is not None else RoundStats()
        engine = self.engines.get(key)
        slots = self.running.get(key, {})
        salvage = [
            (slot, s)
            for slot, s in self.pending.get(key, [])
            if slots.get(slot) is s
        ]
        if salvage and engine is not None and engine.inflight:
            try:
                engine.collect_chunk()
            except recovery.RECOVERABLE:
                salvage = []  # the settled boards are unreachable too
        for slot, s in salvage:
            self._retire_slot(engine, slots, slot, s, stats)
        failed = 0
        for slot, s in list(slots.items()):
            del slots[slot]
            if engine is not None:
                engine.release(slot)
            s.fail(error)
            self._notify_finished(s)
            failed += 1
        self.pending.pop(key, None)
        self._fresh.pop(key, None)
        if engine is not None:
            # a lost chunk's accounting dies with its sessions: a stale
            # entry would misroute later peeks to the double buffer
            engine.clear_lost()
        stats.failed += failed
        if failed or salvage:
            log.warning(
                "serve: chunk fault on %r failed %d session(s), retired %d "
                "already-finished: %s",
                key, failed, len(salvage), error,
            )
        return failed

    def _retire_slot(
        self, engine: EngineBase, slots: dict, slot: int, s: Session,
        stats: RoundStats,
    ) -> None:
        del slots[slot]
        try:
            board = engine.fetch(slot)
        except recovery.RECOVERABLE as e:
            engine.release(slot)
            s.fail(f"fetch failed: {e}")
            self._notify_finished(s)
            stats.failed += 1
            return
        engine.release(slot)
        s.finish(board)
        self._notify_finished(s)
        stats.completed += 1

    def _advance(self, stats: RoundStats) -> None:
        for key, engine in self.engines.items():
            slots = self.running[key]
            if not slots:
                continue
            self._fault_drill(engine, slots, stats)
            if not slots:
                continue
            with obs.span(
                "serve.step-chunk",
                occupied=len(slots),
                steps=engine.chunk_steps,
                **_slot_attrs(slots),
            ):
                try:
                    advanced = engine.dispatch_chunk()
                except recovery.RECOVERABLE as e:
                    # a chunk-level device fault (the chaos engine.* drill,
                    # or any real launch/materialize failure): recovered
                    # IN PLACE — rebuild + replay under the restart
                    # budget, the OOM ladder when applicable — while the
                    # other keys' batches continue untouched; only an
                    # exhausted budget falls back to the typed failure
                    self.recover_engine(key, e, stats)
                    continue
                # account the dispatched steps BEFORE the collect — the
                # same order the pipelined pump uses — so a collect
                # fault's lost-chunk lag (engine.salvage_slot) rewinds
                # exactly what was accounted, under either pump
                for slot, n in advanced.items():
                    s = slots.get(slot)
                    if s is None:
                        continue  # slot freed above; engine already ignores it
                    s.steps_done += n
                    stats.steps_advanced += n
                    if engine.packed:
                        stats.steps_advanced_packed += n
                try:
                    engine.collect_chunk()
                except recovery.RECOVERABLE as e:
                    self.recover_engine(key, e, stats)
                    continue
            with obs.span("serve.retire"):
                for slot, s in list(slots.items()):
                    if s.steps_remaining == 0:
                        self._retire_slot(engine, slots, slot, s, stats)

    # -- the pipelined round (three phases; see the module docstring) -------
    def round_begin(self, keyer, stats: RoundStats) -> list:
        """Locked phase 1: apply parked releases, expire deadlines, admit,
        then async-dispatch one chunk per engine in rotated key order.
        Returns the settle plan — ``(key, engine, rolled)`` per engine that
        has in-flight or pending work — for the service to run outside its
        lock.  Sessions finishing inside a dispatched chunk are recorded
        in ``_fresh``; they retire at the NEXT round's end, once their
        chunk has settled behind its successor."""
        self._process_deferred()
        self.apply_edits(stats)
        now = self.clock()
        with obs.span("serve.admit"):
            self._expire(now, stats)
            self._admit(keyer, stats)
        stats.occupancy = sum(e.occupancy() for e in self.engines.values())
        stats.slots = sum(e.capacity for e in self.engines.values())
        plan = []
        keys = list(self.engines)
        if keys:
            # rotate the dispatch order so no key's chunk is always the
            # last launched — with several compiled programs sharing one
            # device queue, the tail position is the one that waits
            off = self._rotation % len(keys)
            self._rotation += 1
            keys = keys[off:] + keys[:off]
        for key in keys:
            engine = self.engines[key]
            slots = self.running[key]
            if not slots and not engine.inflight and not self.pending.get(key):
                continue
            self._fault_drill(engine, slots, stats)
            if engine.inflight and not engine.ASYNC_ROLL:
                # a host executor still carrying a late-dispatched chunk:
                # dispatching now would run that compute HERE, under the
                # lock — let settle collect it outside, and the end phase
                # launch the next one
                rolled = False
            else:
                rolled = self._dispatch_engine(
                    key, engine, slots, stats, self._fresh
                )
                # a dispatch fault recovered in place replaces the key's
                # engine: the settle plan must carry the LIVE engine, not
                # the condemned one (settling a condemned engine would
                # re-raise and burn another recovery)
                engine = self.engines[key]
            plan.append((key, engine, rolled))
        stats.queue_depth = len(self.queue)
        return plan

    def _dispatch_engine(
        self, key, engine: EngineBase, slots: dict, stats: RoundStats,
        sink: dict,
    ) -> bool:
        """Launch one async chunk on ``engine`` and account its steps to
        the resident sessions; True if a chunk was actually dispatched.
        Sessions the chunk finishes are recorded in ``sink`` — ``_fresh``
        for begin-phase dispatches (their chunk is this round's newest),
        ``pending`` for end-phase ones (the next settle materializes
        them, so they retire at the very next end)."""
        if not any(s.steps_remaining > 0 for s in slots.values()):
            return False
        with obs.span(
            "serve.dispatch",
            occupied=len(slots),
            steps=engine.chunk_steps,
            **_slot_attrs(slots),
        ):
            try:
                advanced = engine.dispatch_chunk()
            except recovery.RECOVERABLE as e:
                # launch-time fault — including the realistic first-
                # compile OOM of a brand-new key, raised HERE inside the
                # locked begin phase: recovered in place (rebuild +
                # replay, OOM ladder), never escaping into the pump; an
                # exhausted budget falls back to the typed failure
                self.recover_engine(key, e, stats)
                return False
        if not advanced:
            return False
        fresh = []
        for slot, n in advanced.items():
            s = slots.get(slot)
            if s is None:
                continue  # slot freed above; the chunk steps dead weight
            s.steps_done += n
            stats.steps_advanced += n
            if engine.packed:
                stats.steps_advanced_packed += n
            if s.steps_remaining == 0:
                fresh.append((slot, s))
        if fresh:
            sink.setdefault(key, []).extend(fresh)
        return True

    def round_end(self, keyer, stats: RoundStats, rolled: set) -> None:
        """Locked phase 3: retire the previous dispatches' finishers
        (their chunks settled in phase 2, so every fetch reads a
        materialized buffer), refill the freed slots from the queue, and
        late-dispatch engines that sat out phase 1 (``rolled`` names the
        keys that already launched a chunk this round — dispatching those
        again would double-step their sessions) — so the drain tail never
        costs a device-idle round per batch generation."""
        with obs.span("serve.retire"):
            for key, entries in list(self.pending.items()):
                engine = self.engines.get(key)
                if engine is None:
                    continue  # key released while its finishers waited
                slots = self.running[key]
                for slot, s in entries:
                    if slots.get(slot) is not s:
                        continue  # cancelled/expired meanwhile; handled there
                    self._retire_slot(engine, slots, slot, s, stats)
            self.pending = self._fresh
            self._fresh = {}
        with obs.span("serve.admit", phase="post-retire"):
            self._admit(keyer, stats)
        for key, engine in self.engines.items():
            slots = self.running[key]
            if slots and not engine.inflight and key not in rolled:
                self._dispatch_engine(key, engine, slots, stats, self.pending)
        stats.queue_depth = len(self.queue)

    def flush_inflight(self) -> None:
        """Collect every engine's in-flight chunk without running a new
        round — the drain tail's last act before close, so no device work
        is abandoned mid-air (e.g. when every session of a chunk was
        cancelled while it flew)."""
        for key, engine in self.engines.items():
            if engine.inflight:
                try:
                    engine.collect_chunk()
                except recovery.RECOVERABLE as e:
                    # the chunk died on its way out: any still-resident
                    # sessions fail typed instead of stranding the drain
                    self.fail_engine_sessions(key, f"{type(e).__name__}: {e}")

    def idle_seconds_delta(self) -> float:
        """Device-idle seconds accumulated across engines since last asked
        (the service drains this into its counter every round)."""
        return sum(e.idle_seconds_delta() for e in self.engines.values())

    def queue_age_oldest_s(self) -> float:
        """Wall age of the oldest still-queued session (0.0 when the
        queue is empty) — the head-of-line demand signal the sampled
        time series carries for the autoscaler: depth says how many are
        waiting, age says how badly the fleet is behind."""
        if not self.queue:
            return 0.0
        now = self.clock()
        return max(0.0, now - min(s.submitted_at for s in self.queue))

    def _notify_finished(self, session: Session) -> None:
        """Tell the observer a session the scheduler drove reached a
        terminal state, with its submit-to-finish latency."""
        if self.observer is not None:
            self.observer.session_finished(
                session, max(0.0, self.clock() - session.submitted_at)
            )

    def release_idle_engines(self) -> int:
        """Drop engines with no resident sessions; returns how many.

        Engines are created lazily per CompileKey and a long-lived service
        with varied client geometries would otherwise accumulate one
        (capacity, h, w) device batch + compiled program per key forever.
        Releasing an idle engine frees its device memory at the cost of a
        recompile if that key's traffic returns — so this is an explicit
        API for quiet periods, never called automatically mid-burst.
        """
        # a queued session for a released key just rebuilds the engine next
        # round (one recompile) — no need to scan the queue here.  Engines
        # mid-settle (the pump's unlocked window holds no service lock, so
        # this call CAN overlap it) are skipped: collecting or deleting one
        # under the pump would race its compute — next quiet call gets it.
        idle_keys = [
            k for k, slots in self.running.items()
            if not slots and not self.engines[k].busy
        ]
        for k in idle_keys:
            engine = self.engines[k]
            if engine.inflight:
                # don't strand a dispatched chunk mid-air (every session of
                # it was cancelled): wait it out before dropping the engine
                try:
                    engine.collect_chunk()
                except recovery.RECOVERABLE:
                    pass  # no residents by construction; the engine dies anyway
            del self.engines[k]
            del self.running[k]
            self.pending.pop(k, None)
            self._fresh.pop(k, None)
        return len(idle_keys)

    # -- introspection -----------------------------------------------------
    def idle(self) -> bool:
        # parked releases count as live work: one more begin phase applies
        # them, so a drain loop cannot exit with slots still held
        return (
            not self.queue
            and not self.deferred
            and all(not s for s in self.running.values())
        )

    def compile_counts(self) -> dict[CompileKey, int]:
        return {k: e.compile_count for k, e in self.engines.items()}
