"""The serve-tier spill store: crash-durable session state on disk.

Durable sessions (docs/SERVING.md "durability", docs/FLEET.md
"failover") rest on the runtime's crash-consistent snapshot contract
(``runtime.checkpoint``): a spilled session is a **board file in the
contract codec** — atomic publish, CRC32 sidecar, intact-check demotion —
plus a tiny JSON manifest carrying everything a *different* process
needs to resume the trajectory bit-exactly:

- the rule spec (``get_rule`` round-trips every registered name and
  parameterized ``noisy:`` spec),
- the absolute step budget and the PRNG ``seed`` / ising ``temperature``
  (the counter-based key schedule makes a mid-stream restart re-enter
  the exact stream — docs/STOCHASTIC.md),
- the remaining deadline budget at spill time (deadlines are
  monotonic-clock absolutes and do not survive a process boundary).

The snapshot's own sidecar records the **absolute completed step** the
board corresponds to, so ``steps remaining = steps_total - step`` and a
resumed deterministic rule (pure function of the board) or stochastic
rule (pure function of ``(seed, step, cell, substream)``) finishes
byte-identical to the uninterrupted run.

Layout: ``<root>/<sid>/board_<step>.txt`` (+ ``.json`` / ``.crc``
sidecars) and ``<root>/<sid>/manifest.json``.  Retention keeps the
newest two snapshots per session (``prune_snapshots``); retire / cancel
/ failure deletes the whole session directory — a spill outliving its
session is exactly the resurrection bug failover must not have.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from tpu_life import chaos
from tpu_life.io.codec import read_board
from tpu_life.runtime.checkpoint import (
    atomic_publish,
    crc_path,
    list_snapshots,
    prune_snapshots,
    save_snapshot,
    snapshot_intact,
    snapshot_path,
)
from tpu_life.runtime.metrics import log

#: Snapshots retained per session (newest N): one extra generation so a
#: crash mid-publish of the newest still leaves an intact predecessor.
KEEP_SNAPSHOTS = 2

MANIFEST = "manifest.json"

#: Marker published when spill is disabled for a session (a write failure
#: — ENOSPC, a dead disk).  The session keeps running WITHOUT durability;
#: the marker makes the degradation visible to the migration tier, which
#: answers the sid's post-death requests 410 ``spill_disabled`` instead
#: of the misleading ``never_snapshotted``.
DISABLED = "DISABLED.json"


class SpillBackend:
    """The pluggable durability seam (docs/FLEET.md "Cross-host
    topology"): where a worker's spilled sessions live.

    Two implementations ship: :class:`SpillStore` (a local directory —
    the default, and the only choice when the rescuing migrator shares a
    filesystem with the victim) and
    :class:`tpu_life.serve.spill_http.HttpSpillBackend` (a remote HTTP
    object store any worker or supervisor can host, so migration works
    when the survivor is on another machine).  Both speak the same
    contract the service's spill pass relies on:

    - ``save`` publishes atomically with a CRC32 witness and returns
      False for a no-op rewrite of the newest spilled step;
    - any write failure raises :class:`OSError` — the service catches it
      in the unlocked settle window and degrades THAT session to
      spill-disabled (the pump never stalls, the worker never dies over
      durability);
    - ``mark_disabled`` / ``delete`` are best-effort terminal
      transitions; ``spilled_count`` / ``spilled_sids`` feed the gauges.
    """

    def save(
        self,
        sid: str,
        board: np.ndarray,
        step: int,
        *,
        rule: str,
        steps_total: int,
        seed: int | None,
        temperature: float | None,
        timeout_s: float | None,
        trace_id: str | None = None,
        edits: list | None = None,
        scheduled_edits: list | None = None,
        stream_seq: int = 0,
    ) -> bool:
        raise NotImplementedError

    def mark_disabled(self, sid: str) -> None:
        raise NotImplementedError

    def delete(self, sid: str) -> None:
        raise NotImplementedError

    def spilled_count(self) -> int:
        raise NotImplementedError

    def spilled_sids(self) -> list[str]:
        raise NotImplementedError

    #: Shard-wise spill capability (docs/SERVING.md "Mega-board
    #: sessions"): backends that can persist a mega-board session as
    #: per-shard tiles override :meth:`save_mesh` and flip this True.
    #: The service checks the flag before a mesh session's spill round —
    #: a backend without the tile contract (the remote HTTP store, for
    #: now) degrades that session to spill-disabled rather than
    #: gathering the full board just to ship it.
    SUPPORTS_MESH = False

    def save_mesh(
        self,
        sid: str,
        tiles,
        step: int,
        *,
        rule: str,
        steps_total: int,
        seed: int | None,
        temperature: float | None,
        timeout_s: float | None,
        height: int,
        width: int,
        mesh: tuple[int, int],
        trace_id: str | None = None,
        edits: list | None = None,
        scheduled_edits: list | None = None,
        stream_seq: int = 0,
    ) -> bool:
        raise NotImplementedError(
            "this spill backend has no shard-wise tile contract"
        )


def make_spill_backend(
    *,
    spill_dir: str | None = None,
    spill_url: str | None = None,
    namespace: str | None = None,
    replicas: int = 1,
) -> "SpillBackend":
    """The one place a serve config becomes a backend: a ``spill_url``
    selects the remote HTTP store (``namespace`` names this worker
    incarnation's slice of it), otherwise the local directory —
    replicated across ``replicas`` sub-stores when > 1
    (``--spill-replicas``).  Both stores at once is a typed config error
    — the session would be split across two stores and neither would
    hold a resumable whole."""
    if spill_url is not None and spill_dir is not None:
        raise ValueError(
            "spill_dir and spill_url are mutually exclusive — a session "
            "spilled half-local, half-remote could never be resumed whole"
        )
    if replicas < 1:
        raise ValueError(f"spill replicas must be >= 1, got {replicas}")
    if spill_url is not None:
        if replicas > 1:
            raise ValueError(
                "spill replication is a local-directory feature; the "
                "remote HTTP store owns its own durability"
            )
        from tpu_life.serve.spill_http import HttpSpillBackend

        return HttpSpillBackend(spill_url, namespace or "default")
    if replicas > 1:
        return ReplicatedSpillBackend(spill_dir, replicas)
    return SpillStore(spill_dir)


@dataclass(frozen=True)
class SpillRecord:
    """One resumable session read back from a spill directory."""

    sid: str  # the spilling worker's own session id
    rule: str  # rule spec (round-trips through get_rule)
    board: np.ndarray  # board at ``step`` (int8, contract codec bytes)
    step: int  # absolute steps completed at the snapshot
    steps_total: int  # absolute step budget of the whole session
    seed: int | None
    temperature: float | None
    timeout_s: float | None  # deadline budget remaining at spill time
    height: int
    width: int
    #: distributed-trace context (docs/OBSERVABILITY.md): persisting it
    #: here is what lets a migrated resume CONTINUE the dead worker's
    #: trace instead of starting a fresh one — None for pre-trace spills
    trace_id: str | None = None
    #: the steered-session fields (docs/STREAMING.md): the applied edit
    #: log (steps <= ``step``; already baked into ``board`` — carried for
    #: provenance), the not-yet-applied tail the survivor must re-apply
    #: at exactly the recorded steps, and the stream-sequence floor a
    #: reconnected watcher's gapless numbering continues from.  None /
    #: None / 0 for never-steered, never-watched sessions (the manifest
    #: omits the keys entirely, keeping pre-stream manifests byte-stable).
    edits: list | None = None
    scheduled_edits: list | None = None
    stream_seq: int = 0

    @property
    def remaining(self) -> int:
        return max(0, self.steps_total - self.step)


def _tile_dirname(r0: int, c0: int) -> str:
    return f"tile_r{int(r0):09d}_c{int(c0):09d}"


@dataclass(frozen=True)
class MeshSpillRecord:
    """One resumable mega-board session read back from a tile-set spill
    (docs/SERVING.md "Mega-board sessions").

    Unlike :class:`SpillRecord` it carries **no board**: the tiles stay
    on disk and :meth:`block_loader` hands out a rectangular reader the
    resuming mesh feeds to ``MeshEngine.load_tiles`` — each destination
    shard pulls exactly its own cell rectangle (possibly on a different
    mesh shape than the one that spilled; arXiv 2112.01075), so the full
    board is never materialized on one host on either side.
    """

    sid: str
    rule: str
    step: int  # absolute steps completed at the chosen tile epoch
    steps_total: int
    seed: int | None
    temperature: float | None
    timeout_s: float | None
    height: int
    width: int
    mesh_shape: tuple[int, int]  # the SPILLING mesh's shape (provenance)
    tiles: tuple  # ((r0, c0, th, tw), ...) covering the board
    root: Path  # the session's spill directory (holds the tile dirs)
    trace_id: str | None = None
    scheduled_edits: list | None = None
    stream_seq: int = 0

    @property
    def remaining(self) -> int:
        return max(0, self.steps_total - self.step)

    def block_loader(self):
        """``load_block(r0, r1, c0, c1) -> cells`` over the tile set at
        this record's epoch.  Reads only the tiles the rectangle
        intersects, one at a time (single-tile cache) — the memory high
        water is one tile plus the requested block, never the board."""
        from tpu_life.models.rules import get_rule

        continuous = bool(getattr(get_rule(self.rule), "continuous", False))
        dtype = np.float32 if continuous else np.int8
        step = self.step
        tiles = self.tiles
        root = self.root
        cache: dict = {}

        def load_block(r0: int, r1: int, c0: int, c1: int) -> np.ndarray:
            out = np.zeros((r1 - r0, c1 - c0), dtype=dtype)
            for tr0, tc0, th, tw in tiles:
                ir0, ir1 = max(r0, tr0), min(r1, tr0 + th)
                ic0, ic1 = max(c0, tc0), min(c1, tc0 + tw)
                if ir0 >= ir1 or ic0 >= ic1:
                    continue
                key = (tr0, tc0)
                if key not in cache:
                    cache.clear()  # one tile resident at a time
                    f = snapshot_path(root / _tile_dirname(tr0, tc0), step)
                    cache[key] = read_board(f, th, tw)
                tile = cache[key]
                out[ir0 - r0 : ir1 - r0, ic0 - c0 : ic1 - c0] = tile[
                    ir0 - tr0 : ir1 - tr0, ic0 - tc0 : ic1 - tc0
                ]
            return out

        return load_block


class SpillStore(SpillBackend):
    """Per-session spill directories under one root (one root per worker).

    Writes happen on the pump thread only; ``delete`` may be called from
    verb threads (cancel) — both ends are plain filesystem operations on
    disjoint per-session directories, and every publish is atomic, so no
    extra locking is needed beyond the service's own.
    """

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        # per-sid steps this store wrote (prune only ever touches its own
        # writes — the checkpoint retention contract)
        self._written: dict[str, list[int]] = {}
        # per-sid edit-log length at the last save: a same-step save with
        # a grown log must not dedup away (the queued-edit case)
        self._edit_counts: dict[str, int] = {}

    def save(
        self,
        sid: str,
        board: np.ndarray,
        step: int,
        *,
        rule: str,
        steps_total: int,
        seed: int | None,
        temperature: float | None,
        timeout_s: float | None,
        trace_id: str | None = None,
        edits: list | None = None,
        scheduled_edits: list | None = None,
        stream_seq: int = 0,
    ) -> bool:
        """Spill one session's state; returns False when ``step`` is
        already the newest spilled step (a queued or retire-lagged
        session — rewriting identical bytes would be pure churn).  A
        same-step save with a GROWN edit log still writes: a queued
        session steered before admission changed state the manifest must
        carry, even though its step did not move."""
        written = self._written.setdefault(sid, [])
        edit_count = len(edits or []) + len(scheduled_edits or [])
        if (
            written
            and written[-1] == step
            and self._edit_counts.get(sid, 0) == edit_count
        ):
            return False
        d = self.root / sid
        # chaos seam (docs/CHAOS.md): a disk-full / dead-disk write fails
        # HERE, inside the store, exactly where a real one would — the
        # service's spill pass catches the OSError, counts it, and
        # degrades the session to spill-disabled instead of dying
        chaos.inject("spill.write")
        save_snapshot(d, step, board, rule=rule)
        self._maybe_corrupt(d, step)
        manifest = {
            "sid": sid,
            "rule": rule,
            "steps_total": int(steps_total),
            "seed": seed,
            "temperature": temperature,
            "timeout_s": timeout_s,
            "trace_id": trace_id,
            "height": int(board.shape[0]),
            "width": int(board.shape[1]),
        }
        # the steered-session keys appear ONLY when set: a never-steered,
        # never-watched session's manifest stays byte-stable across PRs
        if edits:
            manifest["edits"] = edits
        if scheduled_edits:
            manifest["scheduled_edits"] = scheduled_edits
        if stream_seq:
            manifest["stream_seq"] = int(stream_seq)
        with atomic_publish(d / MANIFEST) as tmp:
            tmp.write_text(json.dumps(manifest))
        if not written or written[-1] != step:
            written.append(step)
        self._edit_counts[sid] = edit_count
        self._written[sid] = prune_snapshots(d, KEEP_SNAPSHOTS, written)
        return True

    SUPPORTS_MESH = True

    def save_mesh(
        self,
        sid: str,
        tiles,
        step: int,
        *,
        rule: str,
        steps_total: int,
        seed: int | None,
        temperature: float | None,
        timeout_s: float | None,
        height: int,
        width: int,
        mesh: tuple[int, int],
        trace_id: str | None = None,
        edits: list | None = None,
        scheduled_edits: list | None = None,
        stream_seq: int = 0,
    ) -> bool:
        """Shard-wise spill of one mega-board session: ``tiles`` is the
        ``(r0, c0, cells)`` walk from ``MeshEngine.spill_tiles`` — one
        tile per addressable shard, each published atomically into its
        own ``tile_rNNN_cNNN/`` directory with its own CRC32 sidecar,
        then the sharded manifest.  The publish order is the recovery
        contract: the manifest's tile table is only ever written after
        every tile of ``step`` landed, and ``read_mesh_sessions``
        demotes the WHOLE set to the predecessor epoch if any single
        tile of the newest fails its intact check — a resumed mesh
        session is never a mixed-epoch board."""
        written = self._written.setdefault(sid, [])
        edit_count = len(edits or []) + len(scheduled_edits or [])
        if (
            written
            and written[-1] == step
            and self._edit_counts.get(sid, 0) == edit_count
        ):
            return False
        d = self.root / sid
        tile_table = []
        for r0, c0, cells in tiles:
            td = d / _tile_dirname(r0, c0)
            # same chaos seams as the single-board path, fired per tile:
            # each host writes its own shards, so disk-full and disk-rot
            # strike tile-by-tile (docs/CHAOS.md)
            chaos.inject("spill.write")
            save_snapshot(td, step, cells, rule=rule)
            self._maybe_corrupt(td, step)
            tile_table.append(
                [int(r0), int(c0), int(cells.shape[0]), int(cells.shape[1])]
            )
        manifest = {
            "sid": sid,
            "rule": rule,
            "steps_total": int(steps_total),
            "seed": seed,
            "temperature": temperature,
            "timeout_s": timeout_s,
            "trace_id": trace_id,
            "height": int(height),
            "width": int(width),
            "mesh": {
                "shape": [int(mesh[0]), int(mesh[1])],
                "tiles": tile_table,
            },
        }
        if edits:
            manifest["edits"] = edits
        if scheduled_edits:
            manifest["scheduled_edits"] = scheduled_edits
        if stream_seq:
            manifest["stream_seq"] = int(stream_seq)
        with atomic_publish(d / MANIFEST) as tmp:
            tmp.write_text(json.dumps(manifest))
        if not written or written[-1] != step:
            written.append(step)
        self._edit_counts[sid] = edit_count
        pruned = written
        for r0, c0, _ in tiles:
            pruned = prune_snapshots(d / _tile_dirname(r0, c0), KEEP_SNAPSHOTS, written)
        self._written[sid] = pruned
        return True

    def adopt_mesh(self, sid: str, src: str | os.PathLike) -> Path | None:
        """Take ownership of a spilled tile set by renaming it into this
        store under ``sid`` (atomic on one filesystem) — the resume-time
        ownership transfer: the survivor's store now holds the tiles, so
        the victim-directory cleanup finds nothing to delete and the
        adopted session is durable from its first round (no fresh spill
        needed before the next crash).  Returns the adopted directory,
        or None when the rename cannot be done (cross-device, missing
        source) — the caller then reads the tiles in place."""
        dest = self.root / sid
        try:
            os.replace(os.fspath(src), dest)
        except OSError:
            return None
        # seed retention bookkeeping from the adopted tiles so later
        # save_mesh rounds prune the inherited epochs too
        steps: set[int] = set()
        for td in dest.iterdir():
            if td.is_dir() and td.name.startswith("tile_"):
                steps.update(step for step, _ in list_snapshots(td))
        self._written[sid] = sorted(steps)
        return dest

    def _maybe_corrupt(self, d: Path, step: int) -> None:
        """Chaos seam: bit-flip (or truncate) the just-published snapshot
        bytes — the disk-rot drill.  The CRC sidecar stays truthful to the
        ORIGINAL bytes, so the intact check must demote this snapshot to
        its predecessor instead of resuming garbage."""
        if not chaos.armed():
            return
        p = snapshot_path(d, step)
        data = p.read_bytes()
        mangled = chaos.corrupt("snapshot.corrupt", data)
        if mangled is not data:
            p.write_bytes(mangled)

    def mark_disabled(self, sid: str) -> None:
        """Degrade one session to spill-disabled (a write failure — the
        disk is full or dying): its snapshots are dropped — bytes we can
        no longer keep fresh must not masquerade as a recovery point —
        and a marker is published so a post-death migration answers the
        truthful 410 ``spill_disabled``.  Best-effort: on a disk this
        broken even the marker write may fail, which degrades the reason
        to ``never_snapshotted`` — still a truthful 410."""
        self._written.pop(sid, None)
        self._edit_counts.pop(sid, None)
        d = self.root / sid
        try:
            if d.exists():
                for step, f in list_snapshots(d):
                    f.unlink(missing_ok=True)
                    f.with_suffix(".json").unlink(missing_ok=True)
                    crc_path(f).unlink(missing_ok=True)
                (d / MANIFEST).unlink(missing_ok=True)
            d.mkdir(parents=True, exist_ok=True)
            with atomic_publish(d / DISABLED) as tmp:
                tmp.write_text(json.dumps({"sid": sid, "reason": "spill_error"}))
        except OSError:
            log.warning("spill: could not publish disabled marker for %s", sid)

    def delete(self, sid: str) -> None:
        """Drop a session's spill (terminal transition: done / failed /
        cancelled) — from here on the session must never resume."""
        self._edit_counts.pop(sid, None)
        if self._written.pop(sid, None) is not None or (self.root / sid).exists():
            shutil.rmtree(self.root / sid, ignore_errors=True)

    def spilled_count(self) -> int:
        return len(self._written)

    def spilled_sids(self) -> list[str]:
        return list(self._written)


#: Replica sub-directory prefix under a replicated spill root:
#: ``<root>/replica-0`` .. ``replica-N-1``, each a complete
#: :class:`SpillStore` layout of its own.
REPLICA_PREFIX = "replica-"


class ReplicatedSpillBackend(SpillBackend):
    """N-way replicated local spill: every write fans through N
    :class:`SpillStore` instances rooted at ``<root>/replica-i`` — same
    atomic publish, same CRC32 witness, N independent copies.

    The failure contract is majority-free reads-any: a write that lands
    on AT LEAST ONE replica is durable (a dead replica disk degrades
    redundancy, not the session), and only when EVERY replica refuses
    does the save raise — the service then degrades that session to
    spill-disabled exactly as with a single store.  The read side
    (:func:`read_spill_sessions` / :func:`read_mesh_sessions`) detects
    the replica layout under a worker's spill dir and merges per sid:
    the intact record with the highest step wins, a torn or bit-rotted
    replica silently demotes to its peers, and a sid is only ``corrupt``
    when NO replica yields a resumable record.  The migrator and the
    mesh resume path are unchanged — they keep calling the same readers
    on the same worker spill directory.
    """

    SUPPORTS_MESH = True

    def __init__(self, root: str | os.PathLike, replicas: int):
        if replicas < 2:
            raise ValueError(
                f"a replicated spill needs >= 2 replicas, got {replicas}"
            )
        self.root = Path(root)
        self.stores = [
            SpillStore(self.root / f"{REPLICA_PREFIX}{i}")
            for i in range(replicas)
        ]

    def _fan_save(self, op: str, sid: str, args, kw) -> bool:
        wrote = False
        errors: list[OSError] = []
        for s in self.stores:
            try:
                wrote = getattr(s, op)(sid, *args, **kw) or wrote
            except OSError as e:
                errors.append(e)
        if errors:
            if len(errors) == len(self.stores):
                # every copy refused: this IS a spill failure — the
                # caller degrades the session like a single-store error
                raise errors[0]
            log.warning(
                "spill: %d/%d replicas failed the %s for %s (%s) — "
                "redundancy degraded, session still durable",
                len(errors),
                len(self.stores),
                op,
                sid,
                errors[0],
            )
        return wrote

    def save(self, sid, board, step, **kw) -> bool:
        return self._fan_save("save", sid, (board, step), kw)

    def save_mesh(self, sid, tiles, step, **kw) -> bool:
        # tiles may be a generator (the mesh spill walk): materialize
        # once so every replica writes the same epoch
        return self._fan_save("save_mesh", sid, (list(tiles), step), kw)

    def mark_disabled(self, sid: str) -> None:
        for s in self.stores:
            s.mark_disabled(sid)

    def delete(self, sid: str) -> None:
        for s in self.stores:
            s.delete(sid)

    def spilled_count(self) -> int:
        return len(self.spilled_sids())

    def spilled_sids(self) -> list[str]:
        sids: set[str] = set()
        for s in self.stores:
            sids.update(s.spilled_sids())
        return sorted(sids)


def _replica_roots(rootp: Path) -> list[Path]:
    """The replica sub-stores under a replicated spill root (empty for a
    plain single-store layout), numerically ordered."""
    if not rootp.is_dir():
        return []
    reps = [
        p
        for p in rootp.iterdir()
        if p.is_dir()
        and p.name.startswith(REPLICA_PREFIX)
        and p.name[len(REPLICA_PREFIX):].isdigit()
    ]
    return sorted(reps, key=lambda p: int(p.name[len(REPLICA_PREFIX):]))


def _merge_replica_reads(outcomes):
    """Fold per-replica ``(records, corrupt, disabled)`` triples into one
    reads-any verdict per sid: best intact record (highest step) wins; a
    disabled marker anywhere wins over stale records (the worker dropped
    those bytes on purpose); ``corrupt`` only when no replica resumes."""
    best: dict[str, object] = {}
    corrupt_sids: set[str] = set()
    disabled_sids: set[str] = set()
    for records, corrupt, disabled in outcomes:
        for rec in records:
            prev = best.get(rec.sid)
            if prev is None or rec.step > prev.step:
                best[rec.sid] = rec
        corrupt_sids.update(corrupt)
        disabled_sids.update(disabled)
    merged = [best[sid] for sid in sorted(best) if sid not in disabled_sids]
    corrupt = sorted(
        s for s in corrupt_sids if s not in best and s not in disabled_sids
    )
    return merged, corrupt, sorted(disabled_sids)


def read_spill_sessions(
    root: str | os.PathLike,
) -> tuple[list[SpillRecord], list[str], list[str]]:
    """Read every resumable session under a (dead worker's) spill root.

    Returns ``(records, corrupt_sids, disabled_sids)``: a session whose
    manifest is unreadable or whose snapshots all fail the intact check
    (size + CRC) lands in ``corrupt_sids`` — the migration tier answers
    those with a typed 410 ``spill_corrupt`` instead of resuming
    garbage — and a session the worker degraded to spill-disabled (a
    write failure; the :data:`DISABLED` marker) lands in
    ``disabled_sids`` (410 ``spill_disabled``).  A corrupt *newest*
    snapshot with an intact predecessor demotes silently (the
    recovery-point moves back one spill interval — the same contract as
    directory resume).
    """
    rootp = Path(root)
    reps = _replica_roots(rootp)
    if reps:
        # a replicated layout (docs/FLEET.md): merge per-replica reads —
        # the migrator's call site is unchanged, reads-any happens here
        return _merge_replica_reads([read_spill_sessions(r) for r in reps])
    records: list[SpillRecord] = []
    corrupt: list[str] = []
    disabled: list[str] = []
    if not rootp.is_dir():
        return records, corrupt, disabled
    for d in sorted(p for p in rootp.iterdir() if p.is_dir()):
        sid = d.name
        if (d / DISABLED).exists():
            # ownership split with read_mesh_sessions so a dead worker's
            # scan never reports the same sid twice: tile sets belong to
            # the mesh reader, everything else (including a dir whose
            # manifest is unreadable) lands here
            if not _is_mesh_dir(d):
                disabled.append(sid)
            continue
        try:
            # chaos seam: a read failure on the rescue path — the whole
            # session must land in ``corrupt`` (never crash the migration
            # run, never delete bytes nobody decoded)
            chaos.inject("spill.read")
            meta = json.loads((d / MANIFEST).read_text())
            height = int(meta["height"])
            width = int(meta["width"])
            steps_total = int(meta["steps_total"])
            rule = str(meta["rule"])
        except (OSError, ValueError, KeyError, TypeError):
            log.warning("spill: %s has no readable manifest; corrupt", d)
            corrupt.append(sid)
            continue
        if "mesh" in meta:
            # a shard-wise tile set (docs/SERVING.md "Mega-board
            # sessions") — read_mesh_sessions owns those; classifying
            # the absent top-level board file as corrupt would be wrong
            continue
        chosen = None
        for step, f in list_snapshots(d):  # newest first
            if snapshot_intact(f, height, width):
                chosen = (step, f)
                break
            log.warning("spill: %s failed the intact check; demoting", f)
        if chosen is None:
            corrupt.append(sid)
            continue
        step, f = chosen
        try:
            board = read_board(f, height, width)
        except (OSError, ValueError):
            corrupt.append(sid)
            continue
        seed = meta.get("seed")
        temperature = meta.get("temperature")
        timeout_s = meta.get("timeout_s")
        trace_id = meta.get("trace_id")
        records.append(
            SpillRecord(
                sid=sid,
                rule=rule,
                board=board,
                step=step,
                steps_total=steps_total,
                seed=None if seed is None else int(seed),
                temperature=None if temperature is None else float(temperature),
                timeout_s=None if timeout_s is None else float(timeout_s),
                height=height,
                width=width,
                trace_id=None if trace_id is None else str(trace_id),
                edits=meta.get("edits"),
                scheduled_edits=meta.get("scheduled_edits"),
                stream_seq=int(meta.get("stream_seq", 0)),
            )
        )
    return records, corrupt, disabled


def _is_mesh_dir(d: Path) -> bool:
    """Whether the session dir's manifest marks a shard-wise tile set —
    the ownership test splitting disabled dirs between
    :func:`read_spill_sessions` and :func:`read_mesh_sessions`."""
    try:
        return "mesh" in json.loads((d / MANIFEST).read_text())
    except (OSError, ValueError, TypeError):
        return False


def read_mesh_sessions(
    root: str | os.PathLike,
) -> tuple[list[MeshSpillRecord], list[str], list[str]]:
    """Read every resumable mega-board (tile-set) session under a spill
    root — the shard-wise twin of :func:`read_spill_sessions`, same
    ``(records, corrupt_sids, disabled_sids)`` contract.

    Epoch choice is all-or-nothing per step: the newest step at which
    EVERY tile passes the intact check (size + CRC32) wins; one
    bit-flipped tile demotes the whole set to the predecessor epoch — a
    resumed mesh session is never a mixed-epoch board.  No tile bytes
    are read here: records carry a :meth:`MeshSpillRecord.block_loader`
    so the resuming mesh pulls rectangles tile-by-tile at admission.
    """
    rootp = Path(root)
    reps = _replica_roots(rootp)
    if reps:
        return _merge_replica_reads([read_mesh_sessions(r) for r in reps])
    if not rootp.is_dir():
        return [], [], []
    return _read_mesh_dirs(
        sorted(p for p in rootp.iterdir() if p.is_dir())
    )


def read_mesh_session_dir(d: str | os.PathLike) -> MeshSpillRecord:
    """Read ONE tile-set session directory (the ``resume_tiles_dir``
    pointer a mesh resume submission carries) — same demotion contract
    as :func:`read_mesh_sessions`, but a non-resumable set is a typed
    ValueError (the gateway's 400), because a caller naming a specific
    directory asked for exactly it."""
    dp = Path(d)
    records, corrupt, disabled = _read_mesh_dirs([dp])
    if disabled:
        raise ValueError(f"tile set at {dp} is spill-disabled; not resumable")
    if corrupt or not records:
        raise ValueError(
            f"no resumable tile set at {dp} (missing, corrupt, or not a "
            f"mesh spill)"
        )
    return records[0]


def _read_mesh_dirs(
    dirs,
) -> tuple[list[MeshSpillRecord], list[str], list[str]]:
    records: list[MeshSpillRecord] = []
    corrupt: list[str] = []
    disabled: list[str] = []
    for d in dirs:
        sid = d.name
        if (d / DISABLED).exists():
            # mirror of read_spill_sessions' ownership split: only claim
            # the dir when the manifest says it is a tile set
            if _is_mesh_dir(d):
                disabled.append(sid)
            continue
        try:
            chaos.inject("spill.read")
            meta = json.loads((d / MANIFEST).read_text())
            if "mesh" not in meta:
                continue  # a single-board spill; read_spill_sessions owns it
            height = int(meta["height"])
            width = int(meta["width"])
            steps_total = int(meta["steps_total"])
            rule = str(meta["rule"])
            mesh_shape = tuple(int(v) for v in meta["mesh"]["shape"])
            tiles = tuple(
                (int(r0), int(c0), int(th), int(tw))
                for r0, c0, th, tw in meta["mesh"]["tiles"]
            )
        except (OSError, ValueError, KeyError, TypeError):
            log.warning("spill: %s has no readable mesh manifest; corrupt", d)
            corrupt.append(sid)
            continue
        if not tiles:
            corrupt.append(sid)
            continue
        # candidate epochs: steps present in EVERY tile directory,
        # newest first (a step missing from any tile never qualifies)
        step_sets = []
        for r0, c0, _th, _tw in tiles:
            td = d / _tile_dirname(r0, c0)
            step_sets.append({step for step, _ in list_snapshots(td)})
        common = set.intersection(*step_sets) if step_sets else set()
        chosen = None
        for step in sorted(common, reverse=True):
            ok = True
            for r0, c0, th, tw in tiles:
                f = snapshot_path(d / _tile_dirname(r0, c0), step)
                if not snapshot_intact(f, th, tw):
                    log.warning(
                        "spill: %s failed the intact check; demoting the "
                        "whole tile set past epoch %d",
                        f,
                        step,
                    )
                    ok = False
                    break
            if ok:
                chosen = step
                break
        if chosen is None:
            corrupt.append(sid)
            continue
        seed = meta.get("seed")
        temperature = meta.get("temperature")
        timeout_s = meta.get("timeout_s")
        trace_id = meta.get("trace_id")
        records.append(
            MeshSpillRecord(
                sid=sid,
                rule=rule,
                step=chosen,
                steps_total=steps_total,
                seed=None if seed is None else int(seed),
                temperature=None if temperature is None else float(temperature),
                timeout_s=None if timeout_s is None else float(timeout_s),
                height=height,
                width=width,
                mesh_shape=(mesh_shape[0], mesh_shape[1]),
                tiles=tiles,
                root=d,
                trace_id=None if trace_id is None else str(trace_id),
                scheduled_edits=meta.get("scheduled_edits"),
                stream_seq=int(meta.get("stream_seq", 0)),
            )
        )
    return records, corrupt, disabled
