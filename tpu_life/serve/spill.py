"""The serve-tier spill store: crash-durable session state on disk.

Durable sessions (docs/SERVING.md "durability", docs/FLEET.md
"failover") rest on the runtime's crash-consistent snapshot contract
(``runtime.checkpoint``): a spilled session is a **board file in the
contract codec** — atomic publish, CRC32 sidecar, intact-check demotion —
plus a tiny JSON manifest carrying everything a *different* process
needs to resume the trajectory bit-exactly:

- the rule spec (``get_rule`` round-trips every registered name and
  parameterized ``noisy:`` spec),
- the absolute step budget and the PRNG ``seed`` / ising ``temperature``
  (the counter-based key schedule makes a mid-stream restart re-enter
  the exact stream — docs/STOCHASTIC.md),
- the remaining deadline budget at spill time (deadlines are
  monotonic-clock absolutes and do not survive a process boundary).

The snapshot's own sidecar records the **absolute completed step** the
board corresponds to, so ``steps remaining = steps_total - step`` and a
resumed deterministic rule (pure function of the board) or stochastic
rule (pure function of ``(seed, step, cell, substream)``) finishes
byte-identical to the uninterrupted run.

Layout: ``<root>/<sid>/board_<step>.txt`` (+ ``.json`` / ``.crc``
sidecars) and ``<root>/<sid>/manifest.json``.  Retention keeps the
newest two snapshots per session (``prune_snapshots``); retire / cancel
/ failure deletes the whole session directory — a spill outliving its
session is exactly the resurrection bug failover must not have.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from tpu_life import chaos
from tpu_life.io.codec import read_board
from tpu_life.runtime.checkpoint import (
    atomic_publish,
    crc_path,
    list_snapshots,
    prune_snapshots,
    save_snapshot,
    snapshot_intact,
    snapshot_path,
)
from tpu_life.runtime.metrics import log

#: Snapshots retained per session (newest N): one extra generation so a
#: crash mid-publish of the newest still leaves an intact predecessor.
KEEP_SNAPSHOTS = 2

MANIFEST = "manifest.json"

#: Marker published when spill is disabled for a session (a write failure
#: — ENOSPC, a dead disk).  The session keeps running WITHOUT durability;
#: the marker makes the degradation visible to the migration tier, which
#: answers the sid's post-death requests 410 ``spill_disabled`` instead
#: of the misleading ``never_snapshotted``.
DISABLED = "DISABLED.json"


class SpillBackend:
    """The pluggable durability seam (docs/FLEET.md "Cross-host
    topology"): where a worker's spilled sessions live.

    Two implementations ship: :class:`SpillStore` (a local directory —
    the default, and the only choice when the rescuing migrator shares a
    filesystem with the victim) and
    :class:`tpu_life.serve.spill_http.HttpSpillBackend` (a remote HTTP
    object store any worker or supervisor can host, so migration works
    when the survivor is on another machine).  Both speak the same
    contract the service's spill pass relies on:

    - ``save`` publishes atomically with a CRC32 witness and returns
      False for a no-op rewrite of the newest spilled step;
    - any write failure raises :class:`OSError` — the service catches it
      in the unlocked settle window and degrades THAT session to
      spill-disabled (the pump never stalls, the worker never dies over
      durability);
    - ``mark_disabled`` / ``delete`` are best-effort terminal
      transitions; ``spilled_count`` / ``spilled_sids`` feed the gauges.
    """

    def save(
        self,
        sid: str,
        board: np.ndarray,
        step: int,
        *,
        rule: str,
        steps_total: int,
        seed: int | None,
        temperature: float | None,
        timeout_s: float | None,
        trace_id: str | None = None,
        edits: list | None = None,
        scheduled_edits: list | None = None,
        stream_seq: int = 0,
    ) -> bool:
        raise NotImplementedError

    def mark_disabled(self, sid: str) -> None:
        raise NotImplementedError

    def delete(self, sid: str) -> None:
        raise NotImplementedError

    def spilled_count(self) -> int:
        raise NotImplementedError

    def spilled_sids(self) -> list[str]:
        raise NotImplementedError


def make_spill_backend(
    *,
    spill_dir: str | None = None,
    spill_url: str | None = None,
    namespace: str | None = None,
) -> "SpillBackend":
    """The one place a serve config becomes a backend: a ``spill_url``
    selects the remote HTTP store (``namespace`` names this worker
    incarnation's slice of it), otherwise the local directory.  Both at
    once is a typed config error — the session would be split across two
    stores and neither would hold a resumable whole."""
    if spill_url is not None and spill_dir is not None:
        raise ValueError(
            "spill_dir and spill_url are mutually exclusive — a session "
            "spilled half-local, half-remote could never be resumed whole"
        )
    if spill_url is not None:
        from tpu_life.serve.spill_http import HttpSpillBackend

        return HttpSpillBackend(spill_url, namespace or "default")
    return SpillStore(spill_dir)


@dataclass(frozen=True)
class SpillRecord:
    """One resumable session read back from a spill directory."""

    sid: str  # the spilling worker's own session id
    rule: str  # rule spec (round-trips through get_rule)
    board: np.ndarray  # board at ``step`` (int8, contract codec bytes)
    step: int  # absolute steps completed at the snapshot
    steps_total: int  # absolute step budget of the whole session
    seed: int | None
    temperature: float | None
    timeout_s: float | None  # deadline budget remaining at spill time
    height: int
    width: int
    #: distributed-trace context (docs/OBSERVABILITY.md): persisting it
    #: here is what lets a migrated resume CONTINUE the dead worker's
    #: trace instead of starting a fresh one — None for pre-trace spills
    trace_id: str | None = None
    #: the steered-session fields (docs/STREAMING.md): the applied edit
    #: log (steps <= ``step``; already baked into ``board`` — carried for
    #: provenance), the not-yet-applied tail the survivor must re-apply
    #: at exactly the recorded steps, and the stream-sequence floor a
    #: reconnected watcher's gapless numbering continues from.  None /
    #: None / 0 for never-steered, never-watched sessions (the manifest
    #: omits the keys entirely, keeping pre-stream manifests byte-stable).
    edits: list | None = None
    scheduled_edits: list | None = None
    stream_seq: int = 0

    @property
    def remaining(self) -> int:
        return max(0, self.steps_total - self.step)


class SpillStore(SpillBackend):
    """Per-session spill directories under one root (one root per worker).

    Writes happen on the pump thread only; ``delete`` may be called from
    verb threads (cancel) — both ends are plain filesystem operations on
    disjoint per-session directories, and every publish is atomic, so no
    extra locking is needed beyond the service's own.
    """

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        # per-sid steps this store wrote (prune only ever touches its own
        # writes — the checkpoint retention contract)
        self._written: dict[str, list[int]] = {}
        # per-sid edit-log length at the last save: a same-step save with
        # a grown log must not dedup away (the queued-edit case)
        self._edit_counts: dict[str, int] = {}

    def save(
        self,
        sid: str,
        board: np.ndarray,
        step: int,
        *,
        rule: str,
        steps_total: int,
        seed: int | None,
        temperature: float | None,
        timeout_s: float | None,
        trace_id: str | None = None,
        edits: list | None = None,
        scheduled_edits: list | None = None,
        stream_seq: int = 0,
    ) -> bool:
        """Spill one session's state; returns False when ``step`` is
        already the newest spilled step (a queued or retire-lagged
        session — rewriting identical bytes would be pure churn).  A
        same-step save with a GROWN edit log still writes: a queued
        session steered before admission changed state the manifest must
        carry, even though its step did not move."""
        written = self._written.setdefault(sid, [])
        edit_count = len(edits or []) + len(scheduled_edits or [])
        if (
            written
            and written[-1] == step
            and self._edit_counts.get(sid, 0) == edit_count
        ):
            return False
        d = self.root / sid
        # chaos seam (docs/CHAOS.md): a disk-full / dead-disk write fails
        # HERE, inside the store, exactly where a real one would — the
        # service's spill pass catches the OSError, counts it, and
        # degrades the session to spill-disabled instead of dying
        chaos.inject("spill.write")
        save_snapshot(d, step, board, rule=rule)
        self._maybe_corrupt(d, step)
        manifest = {
            "sid": sid,
            "rule": rule,
            "steps_total": int(steps_total),
            "seed": seed,
            "temperature": temperature,
            "timeout_s": timeout_s,
            "trace_id": trace_id,
            "height": int(board.shape[0]),
            "width": int(board.shape[1]),
        }
        # the steered-session keys appear ONLY when set: a never-steered,
        # never-watched session's manifest stays byte-stable across PRs
        if edits:
            manifest["edits"] = edits
        if scheduled_edits:
            manifest["scheduled_edits"] = scheduled_edits
        if stream_seq:
            manifest["stream_seq"] = int(stream_seq)
        with atomic_publish(d / MANIFEST) as tmp:
            tmp.write_text(json.dumps(manifest))
        if not written or written[-1] != step:
            written.append(step)
        self._edit_counts[sid] = edit_count
        self._written[sid] = prune_snapshots(d, KEEP_SNAPSHOTS, written)
        return True

    def _maybe_corrupt(self, d: Path, step: int) -> None:
        """Chaos seam: bit-flip (or truncate) the just-published snapshot
        bytes — the disk-rot drill.  The CRC sidecar stays truthful to the
        ORIGINAL bytes, so the intact check must demote this snapshot to
        its predecessor instead of resuming garbage."""
        if not chaos.armed():
            return
        p = snapshot_path(d, step)
        data = p.read_bytes()
        mangled = chaos.corrupt("snapshot.corrupt", data)
        if mangled is not data:
            p.write_bytes(mangled)

    def mark_disabled(self, sid: str) -> None:
        """Degrade one session to spill-disabled (a write failure — the
        disk is full or dying): its snapshots are dropped — bytes we can
        no longer keep fresh must not masquerade as a recovery point —
        and a marker is published so a post-death migration answers the
        truthful 410 ``spill_disabled``.  Best-effort: on a disk this
        broken even the marker write may fail, which degrades the reason
        to ``never_snapshotted`` — still a truthful 410."""
        self._written.pop(sid, None)
        self._edit_counts.pop(sid, None)
        d = self.root / sid
        try:
            if d.exists():
                for step, f in list_snapshots(d):
                    f.unlink(missing_ok=True)
                    f.with_suffix(".json").unlink(missing_ok=True)
                    crc_path(f).unlink(missing_ok=True)
                (d / MANIFEST).unlink(missing_ok=True)
            d.mkdir(parents=True, exist_ok=True)
            with atomic_publish(d / DISABLED) as tmp:
                tmp.write_text(json.dumps({"sid": sid, "reason": "spill_error"}))
        except OSError:
            log.warning("spill: could not publish disabled marker for %s", sid)

    def delete(self, sid: str) -> None:
        """Drop a session's spill (terminal transition: done / failed /
        cancelled) — from here on the session must never resume."""
        self._edit_counts.pop(sid, None)
        if self._written.pop(sid, None) is not None or (self.root / sid).exists():
            shutil.rmtree(self.root / sid, ignore_errors=True)

    def spilled_count(self) -> int:
        return len(self._written)

    def spilled_sids(self) -> list[str]:
        return list(self._written)


def read_spill_sessions(
    root: str | os.PathLike,
) -> tuple[list[SpillRecord], list[str], list[str]]:
    """Read every resumable session under a (dead worker's) spill root.

    Returns ``(records, corrupt_sids, disabled_sids)``: a session whose
    manifest is unreadable or whose snapshots all fail the intact check
    (size + CRC) lands in ``corrupt_sids`` — the migration tier answers
    those with a typed 410 ``spill_corrupt`` instead of resuming
    garbage — and a session the worker degraded to spill-disabled (a
    write failure; the :data:`DISABLED` marker) lands in
    ``disabled_sids`` (410 ``spill_disabled``).  A corrupt *newest*
    snapshot with an intact predecessor demotes silently (the
    recovery-point moves back one spill interval — the same contract as
    directory resume).
    """
    rootp = Path(root)
    records: list[SpillRecord] = []
    corrupt: list[str] = []
    disabled: list[str] = []
    if not rootp.is_dir():
        return records, corrupt, disabled
    for d in sorted(p for p in rootp.iterdir() if p.is_dir()):
        sid = d.name
        if (d / DISABLED).exists():
            disabled.append(sid)
            continue
        try:
            # chaos seam: a read failure on the rescue path — the whole
            # session must land in ``corrupt`` (never crash the migration
            # run, never delete bytes nobody decoded)
            chaos.inject("spill.read")
            meta = json.loads((d / MANIFEST).read_text())
            height = int(meta["height"])
            width = int(meta["width"])
            steps_total = int(meta["steps_total"])
            rule = str(meta["rule"])
        except (OSError, ValueError, KeyError, TypeError):
            log.warning("spill: %s has no readable manifest; corrupt", d)
            corrupt.append(sid)
            continue
        chosen = None
        for step, f in list_snapshots(d):  # newest first
            if snapshot_intact(f, height, width):
                chosen = (step, f)
                break
            log.warning("spill: %s failed the intact check; demoting", f)
        if chosen is None:
            corrupt.append(sid)
            continue
        step, f = chosen
        try:
            board = read_board(f, height, width)
        except (OSError, ValueError):
            corrupt.append(sid)
            continue
        seed = meta.get("seed")
        temperature = meta.get("temperature")
        timeout_s = meta.get("timeout_s")
        trace_id = meta.get("trace_id")
        records.append(
            SpillRecord(
                sid=sid,
                rule=rule,
                board=board,
                step=step,
                steps_total=steps_total,
                seed=None if seed is None else int(seed),
                temperature=None if temperature is None else float(temperature),
                timeout_s=None if timeout_s is None else float(timeout_s),
                height=height,
                width=width,
                trace_id=None if trace_id is None else str(trace_id),
                edits=meta.get("edits"),
                scheduled_edits=meta.get("scheduled_edits"),
                stream_seq=int(meta.get("stream_seq", 0)),
            )
        )
    return records, corrupt, disabled
