"""Session lifecycle: one tenant's simulation request from birth to result.

The reference runs one board per process invocation; here a *session* is
the unit of multi-tenancy — (board, rule, step budget) plus lifecycle
state.  The state machine is small and strictly forward::

    QUEUED ──> RUNNING ──> DONE
       │          │ └────> FAILED     (per-slot failure / deadline eviction)
       │          └──────> CANCELLED  (cancel mid-run frees the slot)
       ├────────> FAILED              (deadline expired while queued)
       └────────> CANCELLED           (cancel before admission)

Terminal states keep either a result board (DONE) or an error string
(FAILED / CANCELLED) — never both.  ``steps_done`` advances in host-sync
chunk increments, the serving analogue of the driver's chunked epoch loop
(``backends.base.drive_runner``).
"""

from __future__ import annotations

import enum
import itertools
from dataclasses import dataclass, field

import numpy as np

from tpu_life.models.rules import Rule
from tpu_life.serve.errors import SessionFailed, UnknownSession


class SessionState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    CANCELLED = "cancelled"


#: States from which a session can never move again.
TERMINAL = frozenset(
    {SessionState.DONE, SessionState.FAILED, SessionState.CANCELLED}
)


@dataclass
class Session:
    sid: str
    board: np.ndarray  # input board (int8, owned copy)
    rule: Rule
    steps: int  # total step budget
    state: SessionState = SessionState.QUEUED
    steps_done: int = 0
    result: np.ndarray | None = None
    error: str | None = None
    submitted_at: float = 0.0
    admitted_at: float | None = None  # when the scheduler gave it a slot
    deadline: float | None = None  # absolute clock time; None = no timeout
    # fault-injection drill (mirrors RunConfig.fault_at): raise a simulated
    # per-slot device failure when the session would cross this step — the
    # fixture behind the "one bad tenant must not kill the batch" tests
    fault_at: int = 0
    slot: int | None = None  # batch slot while RUNNING
    # stochastic-tier state (tpu_life.mc): the PRNG seed the trajectory is
    # replayable from, and the ising temperature (None elsewhere).  Seed is
    # also stamped for seeded-board deterministic sessions so the summary
    # is a full replay record.
    seed: int | None = None
    temperature: float | None = None
    # the execution-path stamp (docs/OBSERVABILITY.md): set at admission
    # from the engine that took the session — True on the bitplane-packed
    # stochastic engines (lanes = spins per uint32 word), False on the
    # int8 roll engines, None for deterministic engines (their packing is
    # a backend knob below the serve layer)
    packed: bool | None = None
    lanes: int | None = None
    # failover resume (docs/FLEET.md): absolute steps already completed by
    # a previous life of this trajectory before this service admitted it.
    # ``steps`` stays the REMAINING budget this service must run; views
    # report absolute progress (start_step + …) so a migrated session's
    # client sees monotone progress across the worker boundary, and the
    # MC engines re-enter the counter-based stream at the exact position.
    start_step: int = 0
    # disk-full graceful degradation (docs/CHAOS.md): set when a spill
    # write for this session failed.  The session keeps running but
    # leaves the spill plan — durability is off for it alone; a worker
    # death after this answers 410 ``spill_disabled``.
    spill_disabled: bool = False
    # spill-on-adopt (docs/FLEET.md): a resumed session (start_step > 0 —
    # it is carrying another worker's rescued trajectory) spills on the
    # FIRST spill-capable round rather than waiting out the cadence, so a
    # back-to-back kill degrades to one extra rescue instead of a 410
    # ``never_snapshotted``.  Cleared after its first successful spill.
    spill_urgent: bool = False
    # the OOM fallback ladder's stamp (docs/SERVING.md "Resource
    # governance"): set when this session's CompileKey was degraded to
    # keep serving through device OOM — ``oom_halved_chunk`` (smaller
    # compiled scan) or ``oom_host_demoted`` (the bit-identical host
    # executor).  Results stay byte-identical; only throughput degrades.
    degraded_reason: str | None = None
    # distributed-trace context (docs/OBSERVABILITY.md "Distributed
    # tracing"): the id naming this session's whole cross-process
    # journey — minted by the router (or gateway) per submitted session,
    # persisted in the spill manifest, and CARRIED ACROSS migration so a
    # resumed session continues the same trace on its survivor.  None
    # for library callers that never asked for one.
    trace_id: str | None = None
    # mid-run steering (docs/STREAMING.md "Edits"): ``pending_edits``
    # holds validated-but-unapplied cell lists from PATCH verbs, drained
    # at the next round boundary through the freeze-mask seam;
    # ``edits`` is the applied log — [(absolute_step, [(r, c, v), ...])]
    # in application order — that spills with the manifest so the
    # bit-reproducibility contract extends to steered sessions (session
    # bytes == a solo run replaying this log); ``scheduled_edits`` is a
    # resumed session's future portion of a prior life's log, re-applied
    # at exactly the recorded steps during re-execution.
    pending_edits: list = field(default_factory=list)
    edits: list = field(default_factory=list)
    scheduled_edits: list = field(default_factory=list)
    # the stream sequence floor: frames a previous life of this session
    # already produced (from the spill manifest), so the survivor's hub
    # continues the same gapless sequence space
    stream_seq: int = 0
    # tenant identity (docs/SERVING.md "Tenant QoS"): the resolved
    # tenant name this session was admitted under — set by the gateway
    # from X-API-Key through the QosPolicy, None for library callers
    # and policy-less deployments.  Rides submit -> router -> worker as
    # a typed field: quota checks, DRR fairness, and the per-tenant
    # observability rows all key on it.
    tenant: str | None = None
    # mega-board tier (docs/SERVING.md "Mega-board sessions"): the mesh
    # slice shape ``(rows, cols)`` this session's board is sharded over,
    # None for single-chip sessions.  Set at submit when the governor's
    # never-fits verdict is converted into a mesh placement; the keyer
    # mints a ``mesh:RxC`` CompileKey from it.
    mesh: tuple[int, int] | None = None
    # shard-wise resume (arXiv 2112.01075): a rectangular block loader
    # ``load_block(r0, r1, c0, c1) -> cells`` over a spilled tile set,
    # consumed once at admission by ``MeshEngine.load_tiles`` — the
    # session re-gathers shard by shard (possibly onto a different mesh
    # shape) and ``board`` stays a placeholder, so the full board is
    # never materialized on this host.  Process-local, never serialized.
    mesh_resume: object | None = None

    @property
    def steps_remaining(self) -> int:
        return max(0, self.steps - self.steps_done)

    def finish(self, board: np.ndarray) -> None:
        self.state = SessionState.DONE
        self.result = board
        self.slot = None

    def fail(self, error: str) -> None:
        self.state = SessionState.FAILED
        self.error = error
        self.slot = None

    def cancel(self) -> None:
        self.state = SessionState.CANCELLED
        self.error = "cancelled by client"
        self.slot = None


@dataclass(frozen=True)
class SessionView:
    """Immutable snapshot returned by ``poll`` — callers never see (or
    mutate) the live Session the scheduler is driving."""

    sid: str
    state: SessionState
    steps: int
    steps_done: int
    result: np.ndarray | None
    error: str | None
    # the rule the session runs under — front-ends need it to label
    # results (an RLE export without its rule header is ambiguous)
    rule: str = ""
    # replay record: the PRNG seed (stochastic or seeded-board sessions)
    # and the ising temperature; None where not applicable
    seed: int | None = None
    temperature: float | None = None
    # execution-path attribution: whether a stochastic session is being
    # stepped by a bitplane-packed engine (and its lane width) — None
    # until admission, and always None for deterministic sessions
    packed: bool | None = None
    lanes: int | None = None
    # the OOM fallback ladder's stamp (None when the key never degraded)
    degraded_reason: str | None = None
    # the distributed-trace id (None when the session carries no trace
    # context) — echoed on the wire so clients and the doctor join on it
    trace_id: str | None = None
    # steering attribution: how many edit-log entries this session has
    # accumulated (0 for never-steered sessions — the wire render gates
    # on it so unsteered responses stay byte-stable)
    edits: int = 0
    # mega-board stamp: "RxC" when the session runs on a mesh slice,
    # None for single-chip sessions (the wire render gates on it)
    mesh: str | None = None
    # tenant stamp (docs/SERVING.md "Tenant QoS"): the resolved tenant
    # name, None for policy-less deployments (the wire render gates on
    # it so prior response shapes stay byte-identical)
    tenant: str | None = None

    @property
    def finished(self) -> bool:
        return self.state in TERMINAL


class SessionStore:
    """Issues ids and owns every session this service ever admitted.

    Terminal sessions stay resident so late ``poll`` calls still resolve;
    ``forget`` lets a long-lived service reclaim delivered results
    (without it a months-running process grows without bound — the
    serving twin of the driver's snapshot-retention concern).
    """

    def __init__(self):
        self._sessions: dict[str, Session] = {}
        self._counter = itertools.count()

    def create(self, **kwargs) -> Session:
        sid = f"s{next(self._counter):06d}"
        s = Session(sid=sid, **kwargs)
        self._sessions[sid] = s
        return s

    def get(self, sid: str) -> Session:
        try:
            return self._sessions[sid]
        except KeyError:
            raise UnknownSession(f"unknown session id {sid!r}") from None

    def view(self, sid: str) -> SessionView:
        s = self.get(sid)
        # absolute step space: a resumed session (start_step > 0) reports
        # total-trajectory progress, so a client polling through a worker
        # migration sees steps_done only ever grow
        return SessionView(
            sid=s.sid,
            state=s.state,
            steps=s.start_step + s.steps,
            steps_done=s.start_step + s.steps_done,
            result=s.result,
            error=s.error,
            rule=s.rule.name,
            seed=s.seed,
            temperature=s.temperature,
            packed=s.packed,
            lanes=s.lanes,
            degraded_reason=s.degraded_reason,
            trace_id=s.trace_id,
            edits=len(s.edits) + len(s.scheduled_edits),
            mesh=(f"{s.mesh[0]}x{s.mesh[1]}" if s.mesh is not None else None),
            tenant=s.tenant,
        )

    def result(self, sid: str) -> np.ndarray:
        """The DONE session's final board, or a typed error explaining why
        there is none (still in flight -> UnknownSession is wrong, so an
        unfinished session raises SessionFailed with a 'not finished'
        message only from FAILED/CANCELLED; in-flight raises ValueError)."""
        s = self.get(sid)
        if s.state is SessionState.DONE:
            assert s.result is not None
            return s.result
        if s.state in TERMINAL:
            raise SessionFailed(
                f"session {sid} {s.state.value}: {s.error or 'no result'}"
            )
        raise ValueError(f"session {sid} still {s.state.value}; poll later")

    def forget(self, sid: str) -> None:
        """Drop a TERMINAL session (delivered results are the caller's now)."""
        s = self.get(sid)
        if s.state not in TERMINAL:
            raise ValueError(f"cannot forget live session {sid} ({s.state.value})")
        del self._sessions[sid]

    def count(self, state: SessionState) -> int:
        return sum(1 for s in self._sessions.values() if s.state is state)

    def live(self) -> list[Session]:
        """Sessions not yet in a terminal state, in submission order."""
        return [s for s in self._sessions.values() if s.state not in TERMINAL]

    def live_by_tenant(self) -> dict[str, int]:
        """Live-session counts keyed by tenant name (sessions without a
        tenant stamp are skipped) — the quota check's and the per-tenant
        gauge's shared input."""
        out: dict[str, int] = {}
        for s in self._sessions.values():
            if s.state in TERMINAL or s.tenant is None:
                continue
            out[s.tenant] = out.get(s.tenant, 0) + 1
        return out

    def __len__(self) -> int:
        return len(self._sessions)
