"""Batched step engines: many tenant lattices through one compiled step.

The Ising-on-TPU lesson (PAPERS.md, arXiv:1903.11714) is that stencil
workloads only saturate an accelerator when many independent lattices ride
one compiled program.  An engine owns a **fixed-capacity padded batch**:
a ``(capacity, h, w)`` int8 array plus a per-slot ``remaining`` step
vector.  Continuous batching falls out of two properties:

- the compiled chunk function has *constant shapes* — capacity, board
  geometry and chunk length never vary — so sessions can join and leave
  between host-sync chunks with **zero recompilation** (the acceptance
  test asserts ``compile_count == 1`` across 20 staggered sessions);
- per-slot step budgets are enforced *inside* the compiled scan by a
  freeze mask (``remaining > 0``): every step, slots whose budget is spent
  keep their board unchanged.  One fused scan therefore advances each
  slot by exactly ``min(chunk_steps, remaining[slot])`` steps — uneven
  budgets with bit-identical results to independent sequential runs.

Three executors behind one interface, mirroring the Backend split:

- :class:`VmapEngine`  — ``jax`` backend: ``vmap`` of the XLA stencil step
  under one jit/scan, the device path;
- :class:`HostBatchEngine` — ``numpy`` backend: the ground-truth executor
  on the same batch layout;
- :class:`SlotLoopEngine` — any other backend (sharded / pallas / native /
  stripes): one ``Runner`` per slot via the existing ``make_runner`` seam,
  advanced slot by slot.  Slower, but keeps the whole backend matrix
  servable without new kernels.

The chunk API is split into a **dispatch / collect contract** so the
pipelined pump (docs/SERVING.md) can overlap device compute with host
work: ``dispatch_chunk()`` *launches* one chunk and returns immediately
with the per-slot step accounting; ``collect_chunk()`` blocks until that
chunk is materialized; ``settle()`` blocks only far enough that
``fetch()`` of *frozen* slots cannot stall (the device executor keeps its
newest chunk in flight; host executors run their deferred compute here —
outside the service lock).  ``advance_chunk()`` = dispatch + collect is
the host-synchronous composition the classic scheduler round still uses.

Double buffering and donation rules: the device executors keep a
reference to the in-flight chunk's *input* batch (``_prev``), so a slot
frozen during the chunk (``remaining == 0`` — its value is provably
unchanged by the freeze mask) can be fetched from ``_prev`` while the
chunk is still executing.  That reference is why the chunk function
donates only its auxiliary carry (``remaining``, and the MC step
counters) and **not** the board batch — donating boards would invalidate
the very buffer late retirement reads.  The slot-writer programs still
donate everything (nothing holds their inputs).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from tpu_life import chaos
from tpu_life.models.rules import Rule
from tpu_life.runtime.metrics import log


@dataclass(frozen=True)
class CompileKey:
    """What must match for two sessions to share one compiled batch.

    Admission groups sessions by this key (scheduler.py); each key owns
    one engine, one compiled program, one set of slots.  ``Rule`` is a
    frozen hashable value, so the key is usable as a dict key directly.
    """

    rule: Rule
    shape: tuple[int, int]  # (height, width)
    dtype: str  # board element type ("int8"; "float32" on the continuous tier)
    backend: str  # executor family ("jax" / "numpy" / "sharded" / ...)
    # the resolved counting path (docs/RULES.md): "roll" shift-adds or
    # "matmul" banded one-hot/weighted matmuls.  Resolved per rule at
    # submit (ServeConfig.stencil through ops.conv.resolve_stencil), so
    # it is a pure function of the other fields + config — it never
    # splits a batch, but it IS part of what the engine compiles.
    stencil: str = "roll"


def compile_key_for(
    rule: Rule, board: np.ndarray, backend: str, stencil: str = "roll"
) -> CompileKey:
    return CompileKey(
        rule=rule,
        shape=(int(board.shape[0]), int(board.shape[1])),
        dtype=rule.board_dtype,
        backend=backend,
        stencil=stencil,
    )


class EngineBase:
    """Slot bookkeeping shared by every executor.

    ``compile_count`` counts builds of the batched step program — the
    expensive event continuous batching exists to avoid.  Tests assert it
    stays at 1 per engine no matter how many sessions churn through.
    """

    #: True for executors whose ``dispatch_chunk`` may be called while a
    #: previous chunk is still in flight (the device path: XLA chains the
    #: chunks on data dependencies, so rolling never blocks the host).
    #: Host executors auto-collect first — their "in-flight" chunk is
    #: deferred *host* compute that would otherwise be silently dropped.
    ASYNC_ROLL = False

    #: Observability stamps (docs/OBSERVABILITY.md): which storage path
    #: this executor steps — ``packed`` True/False on the stochastic
    #: engines (None on deterministic ones, whose packing is a backend
    #: knob below this layer), ``lanes`` the spins-per-word of a packed
    #: engine.  The scheduler copies them onto each admitted session so
    #: round records and session views attribute throughput to the path
    #: that produced it.
    packed: bool | None = None
    lanes: int | None = None

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        self.key = key
        self.capacity = capacity
        self.chunk_steps = chunk_steps
        # the board element dtype this engine stores and steps — int8
        # everywhere but the continuous tier's float32 boards
        self.dtype = np.dtype(getattr(key, "dtype", "int8"))
        # the per-key stencil stamp (docs/OBSERVABILITY.md): which
        # counting path this engine compiled — None on the stochastic
        # engines (their sweep has no counting stencil to route)
        self.stencil = (
            None
            if getattr(key.rule, "stochastic", False)
            else getattr(key, "stencil", "roll")
        )
        self.compile_count = 0
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._remaining = np.zeros(capacity, dtype=np.int64)
        # the in-flight chunk's {slot: steps} accounting (empty = none)
        self._inflight: dict[int, int] = {}
        # a LOST chunk's accounting: collect raised after the in-flight
        # map was already cleared, so these steps are accounted to the
        # sessions but their results are unreachable — the in-place
        # recovery (scheduler.recover_engine) reads this to rewind each
        # session to its newest materialized state.  Empty outside the
        # window between a collect fault and the engine's replacement.
        self._lost: dict[int, int] = {}
        # set by the service while this engine settles OUTSIDE the lock:
        # verb-triggered slot releases must defer to the pump meanwhile
        self.busy = False
        # device-idle bookkeeping: wall time this engine sat with no chunk
        # in flight between a collect and the next dispatch.  Always real
        # time (time.monotonic), independent of any injected test clock —
        # it measures the machine, not the simulated schedule.
        self.idle_seconds = 0.0
        self._idle_reported = 0.0
        self._idle_since: float | None = None

    # -- slot lifecycle ----------------------------------------------------
    def acquire(self) -> int | None:
        """Claim a free slot (None when the batch is full)."""
        return self._free.pop() if self._free else None

    def release(self, slot: int) -> None:
        """Return a slot to the pool; its lattice is dead weight until the
        next load (the freeze mask already ignores it: remaining == 0).
        The slot also leaves any uncollected chunk's accounting: a host
        executor's deferred compute must not step a board that a new
        session is about to be (or already was) loaded into."""
        self._remaining[slot] = 0
        self._inflight.pop(slot, None)
        self._lost.pop(slot, None)
        self._clear_slot(slot)
        self._free.append(slot)

    def occupancy(self) -> int:
        return self.capacity - len(self._free)

    def load(
        self,
        slot: int,
        board: np.ndarray,
        steps: int,
        *,
        seed: int | None = None,
        temperature: float | None = None,
        start_step: int = 0,
    ) -> None:
        """Stage a session's lattice into ``slot`` with ``steps`` budget.

        ``seed``/``temperature``/``start_step`` are the stochastic-tier
        per-slot state (``tpu_life.mc.engine``); deterministic engines
        ignore them — submit-time validation already rejected any
        meaningless combination.
        """
        h, w = self.key.shape
        if board.shape != (h, w):
            raise ValueError(
                f"board shape {board.shape} does not match engine key {self.key.shape}"
            )
        self._remaining[slot] = steps
        self._load_slot(slot, np.asarray(board, self.dtype), steps)

    def remaining(self, slot: int) -> int:
        return int(self._remaining[slot])

    # -- the batched chunk: dispatch / collect ------------------------------
    @property
    def inflight(self) -> bool:
        """True while a dispatched chunk has not been collected."""
        return bool(self._inflight)

    def dispatch_chunk(self) -> dict[int, int]:
        """Launch one chunk that advances every occupied slot by
        ``min(chunk_steps, remaining)`` steps; returns that per-slot
        accounting immediately, without waiting for the result.

        The device executors may be re-dispatched while a previous chunk
        is still in flight (``ASYNC_ROLL``) — XLA executes the chunks
        back-to-back with no host in the loop, which is the whole point
        of the pipelined pump.  Host executors collect first.
        """
        if self._inflight and not self.ASYNC_ROLL:
            self.collect_chunk()
        advanced = {
            s: min(self.chunk_steps, int(r))
            for s, r in enumerate(self._remaining)
            if r > 0
        }
        if advanced:
            # chaos seams: a launch-time device fault, and a launch-time
            # RESOURCE_EXHAUSTED (the OOM drill: first-compile of a new
            # key, or a neighbor key ballooning the heap).  Both raised
            # BEFORE any state moves, so the engine stays consistent
            # (nothing new in flight, remaining untouched) and the
            # scheduler's RECOVERABLE handling recovers this key in
            # place while every other key keeps stepping.
            chaos.inject("engine.dispatch")
            chaos.inject("engine.oom")
            now = time.monotonic()
            if self._idle_since is not None:
                self.idle_seconds += now - self._idle_since
                self._idle_since = None
            self._dispatch_impl()
            self._remaining = np.maximum(self._remaining - self.chunk_steps, 0)
            self._inflight = advanced
        return advanced

    def collect_chunk(self) -> dict[int, int]:
        """Block until the in-flight chunk (if any) is fully materialized;
        returns its {slot: steps} accounting.  After this, ``fetch`` of
        any slot reflects the chunk."""
        adv, self._inflight = self._inflight, {}
        if adv:
            self._chaos_wedge()
            try:
                # chaos seam: the chunk's materialization fails (a device
                # reset mid-chunk).  The in-flight accounting is already
                # cleared, so the handler's slot releases leave the
                # engine re-dispatchable; the chunk's accounting lands in
                # ``_lost`` so in-place recovery can rewind its sessions
                # to their newest materialized state (per-key isolation).
                chaos.inject("engine.collect")
                self._collect_impl(adv)
            except BaseException:
                for slot, n in adv.items():
                    self._lost[slot] = self._lost.get(slot, 0) + n
                raise
            self._idle_since = time.monotonic()
        return adv

    def clear_lost(self) -> None:
        """Forget a lost chunk's accounting — the typed-failure path has
        released (or retired) its sessions, and a stale entry would
        misroute later peeks to the double buffer."""
        self._lost.clear()

    def _chaos_wedge(self) -> None:
        # chaos seam: a wedged grant — the chunk wait stalls instead of
        # raising (the real-TPU probe-hang mode, docs/CHAOS.md).  Fired
        # from collect AND the device settle paths, i.e. wherever the
        # pipelined pump's unlocked window actually blocks — which is
        # what the service's settle-deadline watchdog exists to catch.
        hang = chaos.delay("engine.wedge")
        if hang > 0:
            log.warning("chaos: engine wedging %.1fs (engine.wedge)", hang)
            time.sleep(hang)

    def settle(self) -> None:
        """Finish enough in-flight work that ``fetch()`` of *frozen*
        slots cannot stall.  Host executors run their deferred chunk
        compute here (the pipelined pump calls this outside the service
        lock, so submit/poll stay serviceable meanwhile); the device
        executor overrides to wait for everything but its newest chunk.
        """
        self.collect_chunk()

    def advance_chunk(self) -> dict[int, int]:
        """The host-synchronous composition: dispatch one chunk and wait
        for it — the classic scheduler round's quantum."""
        advanced = self.dispatch_chunk()
        self.collect_chunk()
        return advanced

    def idle_seconds_delta(self) -> float:
        """Idle seconds accumulated since this was last called — the
        service drains these into its ``serve_device_idle_seconds_total``
        counter every round."""
        delta = self.idle_seconds - self._idle_reported
        self._idle_reported = self.idle_seconds
        return delta

    def fetch(self, slot: int) -> np.ndarray:
        """The slot's materialized board — guarded: fetching a slot the
        in-flight chunk is still STEPPING would return pre-chunk data, so
        the scheduler only ever fetches frozen slots (the guard trips on
        a pump bug).  One body for every executor: guard, then the same
        newest-materialized read :meth:`peek_slot` uses — the two paths
        must never diverge."""
        self._fetch_guard(slot)
        return self._peek_board(slot)

    def peek_slot(self, slot: int) -> tuple[np.ndarray, int]:
        """The newest MATERIALIZED board for a resident slot, plus how many
        already-accounted steps that board lags the session bookkeeping
        (the in-flight chunk's steps for this slot; 0 when settled).

        The spill path (``serve.spill``) snapshots live slots with this:
        after ``settle()`` the double buffer is materialized, so peeking
        never blocks on the newest in-flight chunk — the snapshot's
        recovery point is simply one chunk behind the accounting.  Unlike
        :meth:`fetch` there is no in-flight guard: the caller pairs the
        board with the returned lag instead of requiring lag zero.
        """
        return self._peek_board(slot), self._inflight.get(slot, 0)

    def salvage_slot(self, slot: int) -> tuple[np.ndarray, int]:
        """After a chunk-level fault: the newest *trustworthy* board for
        a resident slot, plus how many already-accounted steps it lags
        the session bookkeeping — the in-flight chunk's steps (if any is
        still flying) plus a LOST chunk's (collect raised after clearing
        the in-flight map).  The in-place recovery path
        (``scheduler.recover_engine``) rewinds each session by this lag
        and replays the difference on a rebuilt engine, so a device
        fault costs a re-run of at most two chunks — never a session.
        Materializing the board may itself raise RECOVERABLE (a poisoned
        device buffer): that session is genuinely unrecoverable and the
        caller fails it typed."""
        lag = self._inflight.get(slot, 0) + self._lost.get(slot, 0)
        return self._peek_board(slot), lag

    def _peek_board(self, slot: int) -> np.ndarray:
        raise NotImplementedError

    def _fetch_guard(self, slot: int) -> None:
        # fetching a slot the in-flight chunk is still STEPPING would
        # return pre-chunk data on the host executors; the scheduler only
        # ever fetches frozen slots, so tripping this is a pump bug
        if slot in self._inflight:
            raise RuntimeError(
                f"slot {slot} is being stepped by an in-flight chunk; "
                f"collect_chunk() before fetch"
            )

    # -- executor hooks ----------------------------------------------------
    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        raise NotImplementedError

    def _clear_slot(self, slot: int) -> None:
        raise NotImplementedError

    def _dispatch_impl(self) -> None:
        """Launch (device) or stage (host) one chunk of work."""
        raise NotImplementedError

    def _collect_impl(self, advanced: dict[int, int]) -> None:
        """Materialize the chunk ``_dispatch_impl`` launched; ``advanced``
        is its {slot: steps} accounting (host executors compute from it —
        ``_remaining`` has already been decremented)."""
        raise NotImplementedError


class VmapEngine(EngineBase):
    """The device path: one jitted ``lax.scan`` over the whole batch.

    The batch axis is a plain ``jax.vmap`` over the existing single-board
    stencil step (``ops.stencil.make_step``) — the same jaxpr every
    single-session backend runs, so bit-identity with ``driver.run`` is
    inherited, not re-proven.  Boards stay device-resident between chunks;
    slot loads go through one jitted dynamic-update program (slot index
    traced, so joining a running batch never triggers a retrace).

    Pipelining: dispatch is an async XLA launch, and the pre-chunk board
    batch is retained in ``_prev`` (double buffer) so frozen slots retire
    without waiting for the newest chunk.  ``settle`` waits only for
    ``_prev`` to materialize — i.e. for every chunk but the newest —
    which also bounds the device queue at double-buffer depth.
    """

    ASYNC_ROLL = True

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int):
        super().__init__(key, capacity, chunk_steps)
        import jax
        import jax.numpy as jnp

        h, w = key.shape
        self._jnp = jnp
        # dtype-general batch: int8 for discrete rules, float32 on the
        # continuous tier — everything else (freeze mask, double buffer,
        # slot writer) is dtype-agnostic (jnp accepts numpy dtypes)
        self._dt = self.dtype
        self._boards = jax.device_put(
            jnp.zeros((capacity, h, w), dtype=self._dt)
        )
        self._rem_dev = jax.device_put(jnp.zeros(capacity, dtype=jnp.int32))
        self._prev = None  # the in-flight chunk's input batch (double buffer)

        # slot writer: slot index and budget are traced scalars, so every
        # load/evict reuses one compiled program regardless of which slot
        def set_slot(boards, rem, slot, board, steps):
            return boards.at[slot].set(board), rem.at[slot].set(steps)

        self._set_slot = jax.jit(set_slot, donate_argnums=(0, 1))
        self._chunk = None  # built lazily on first advance

    def _build_chunk(self):
        import jax
        import jax.numpy as jnp

        from tpu_life import obs
        from tpu_life.ops.stencil import make_step

        # the build itself is cheap; the first advance pays the XLA
        # compile — the span marks the event so a serve trace shows which
        # round took the compilation hit for which key
        obs.instant(
            "serve.compile",
            rule=self.key.rule.name,
            shape=f"{self.key.shape[0]}x{self.key.shape[1]}",
            backend=self.key.backend,
            stencil=self.stencil,
        )
        step = jax.vmap(
            make_step(self.key.rule, self.stencil or "roll", self.key.shape)
        )
        length = self.chunk_steps

        def chunk(boards, rem):
            def body(carry, _):
                bs, r = carry
                stepped = step(bs)
                live = (r > 0)[:, None, None]
                bs = jnp.where(live, stepped, bs)
                return (bs, jnp.maximum(r - 1, 0)), None

            (boards, rem), _ = jax.lax.scan(
                body, (boards, rem), None, length=length
            )
            return boards, rem

        self.compile_count += 1
        # donate only the remaining-steps carry: the board input is the
        # double buffer late retirement reads (see the module docstring)
        return jax.jit(chunk, donate_argnums=(1,))

    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        jnp = self._jnp
        self._boards, self._rem_dev = self._set_slot(
            self._boards,
            self._rem_dev,
            jnp.int32(slot),
            jnp.asarray(board, self._dt),
            jnp.int32(steps),
        )

    def _clear_slot(self, slot: int) -> None:
        h, w = self.key.shape
        self._load_slot(slot, np.zeros((h, w), self.dtype), 0)

    def _dispatch_impl(self) -> None:
        if self._chunk is None:
            self._chunk = self._build_chunk()
        self._prev = self._boards
        self._boards, self._rem_dev = self._chunk(self._boards, self._rem_dev)

    def _collect_impl(self, advanced: dict[int, int]) -> None:
        import jax

        jax.block_until_ready(self._boards)
        self._prev = None

    def settle(self) -> None:
        # wait for every chunk but the newest: _prev is the newest chunk's
        # input, i.e. the previous chunk's output — once it is ready, every
        # frozen slot fetches without blocking, and the host can never run
        # more than one chunk ahead of the device
        self._chaos_wedge()
        if self._prev is not None:
            import jax

            jax.block_until_ready(self._prev)

    def _peek_board(self, slot: int) -> np.ndarray:
        # the double buffer is the newest MATERIALIZED state while a chunk
        # flies: a slot frozen in that chunk (remaining == 0 — the freeze
        # mask provably leaves it untouched) has the same value in the
        # chunk INPUT as in its output, so fetch reads here instead of
        # blocking on the newest chunk; a slot the chunk IS stepping reads
        # its pre-chunk state — peek_slot's lag accounts for it.  A LOST
        # chunk (collect raised) reads the same way: _prev is the dead
        # chunk's input and _boards its unreachable output, so salvage
        # must read _prev too.
        if (self._inflight or self._lost) and self._prev is not None:
            return np.asarray(self._prev[slot])
        return np.asarray(self._boards[slot])


class HostBatchEngine(EngineBase):
    """The numpy executor on the same batch layout — the serving twin of
    ``NumpyBackend``, and the truth executor the equivalence tests pin
    the device engine against.  Its chunk "dispatch" only stages the
    work; the compute runs in ``_collect_impl`` — which the pipelined
    pump calls from ``settle()`` *outside* the service lock, so host
    stepping never blocks submit/poll."""

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int):
        super().__init__(key, capacity, chunk_steps)
        h, w = key.shape
        self._boards = np.zeros((capacity, h, w), dtype=self.dtype)
        # the per-slot step function, built ONCE per engine: the numpy
        # roll oracle for discrete keys (bit-identity ground truth), the
        # float oracle for continuous keys, and the matmul counting body
        # when the key's stencil pins it (its band operators are static
        # per key — rebuilding them per step would be pure churn)
        rule = key.rule
        stencil = self.stencil or "roll"
        if getattr(rule, "continuous", False):
            from tpu_life.models.lenia import make_lenia_step

            self._step = make_lenia_step(np, rule, (h, w), stencil)
        elif stencil == "matmul":
            from tpu_life.ops.conv import make_counts_matmul

            counts_fn = make_counts_matmul(np, rule, (h, w))
            table = rule.transition_table
            self._step = lambda b: table[b.astype(np.int64), counts_fn(b)]
        else:
            from tpu_life.ops.reference import step_np

            self._step = lambda b: step_np(b, rule)

    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        self._boards[slot] = board

    def _clear_slot(self, slot: int) -> None:
        self._boards[slot] = 0

    def _dispatch_impl(self) -> None:
        pass  # deferred: the chunk runs at collect time (see class doc)

    def _collect_impl(self, advanced: dict[int, int]) -> None:
        for slot, n in advanced.items():
            b = self._boards[slot]
            for _ in range(n):
                b = self._step(b)
            self._boards[slot] = b

    def _peek_board(self, slot: int) -> np.ndarray:
        # deferred-compute executor: while a chunk is "in flight" (staged,
        # not yet collected) the array still holds the PRE-chunk state,
        # which is exactly what peek_slot's lag accounting expects
        return self._boards[slot].copy()


class SlotLoopEngine(EngineBase):
    """Fallback for backends with no batch axis (sharded / pallas / native
    / stripes): one device-resident ``Runner`` per slot via the existing
    ``make_runner`` seam, advanced slot by slot each chunk.  Compilation
    is the backend's business (each runner compiles its own step), so
    ``compile_count`` stays 0 here by design."""

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int, backend):
        super().__init__(key, capacity, chunk_steps)
        self._backend = backend
        self._runners: dict[int, object] = {}

    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        from tpu_life.backends.base import make_runner

        self._runners[slot] = make_runner(self._backend, board, self.key.rule)

    def _clear_slot(self, slot: int) -> None:
        self._runners.pop(slot, None)

    def _dispatch_impl(self) -> None:
        pass  # deferred: runners advance at collect time, like the host engine

    def _collect_impl(self, advanced: dict[int, int]) -> None:
        for slot, n in advanced.items():
            runner = self._runners.get(slot)
            if runner is not None:  # slot released since dispatch: work is moot
                runner.advance(n)

    def _peek_board(self, slot: int) -> np.ndarray:
        return np.asarray(self._runners[slot].fetch())


def make_host_engine(key: CompileKey, capacity: int, chunk_steps: int) -> EngineBase:
    """The key's host-executor twin — the bottom rung of the OOM
    recovery ladder (docs/SERVING.md "Resource governance"): when a
    device engine OOMs even at a halved chunk, the scheduler demotes
    the key to the bit-identical host executor (``HostBatchEngine`` /
    ``MCHostEngine``) so its sessions *finish*, slower, instead of
    failing typed.  Bit-identity is the ground-truth contract these
    executors already carry — the equivalence suites pin the device
    engines against exactly them."""
    if getattr(key.rule, "stochastic", False):
        from tpu_life.mc.engine import MCHostEngine

        return MCHostEngine(key, capacity, chunk_steps)
    return HostBatchEngine(key, capacity, chunk_steps)


def make_engine(
    key: CompileKey,
    capacity: int,
    chunk_steps: int,
    *,
    mc_packed: bool | None = None,
) -> EngineBase:
    """Engine factory, dispatched on the key's executor family.

    ``backend == "tuned"`` resolves the executor through the autotune
    cache per CompileKey — **read path only** (cache hit or analytic cost
    model): serving latency must never pay measurement cost, so an
    untuned key degrades to the cost-model pick, it does not trigger a
    trial sweep.  Run ``tpu-life tune`` offline to populate the cache.

    ``mc_packed`` is the stochastic tier's bitplane knob
    (``ServeConfig.mc_packed`` / ``--no-bitpack``); deterministic keys
    ignore it.
    """
    if getattr(key.rule, "stochastic", False):
        # stochastic keys dispatch to the MC executors (per-slot seed /
        # temperature / step-counter state); backends without the key
        # schedule are a typed rejection, never a silent fallback
        from tpu_life.mc.engine import make_mc_engine

        return make_mc_engine(key, capacity, chunk_steps, packed=mc_packed)
    if str(key.backend).startswith("mesh:"):
        # mega-board tier (serve/mesh_engine.py): the board is sharded
        # over a mesh:RxC device slice with halo exchange — capacity is
        # pinned to 1 because the mega-board owns the slice, whatever
        # the scheduler's batch capacity is for single-chip engines
        from tpu_life.serve.mesh_engine import MeshEngine

        return MeshEngine(key, chunk_steps)
    if getattr(key.rule, "continuous", False):
        # continuous keys need a float executor (models/lenia.py): the
        # vmapped device batch or the numpy oracle — a slot-loop backend
        # would silently cast float boards to int8, which is junk, so
        # anything else is the typed rejection
        from tpu_life.models.lenia import require_float_path

        backend_name = key.backend
        if backend_name == "tuned":
            from tpu_life import autotune
            from tpu_life.runtime.metrics import log

            tk = autotune.tune_key_for(key.rule, key.shape)
            tuned, source = autotune.resolve(tk, mode="cache", shape=key.shape)
            log.info(
                "serve: autotune %s -> %s (%s)", tk.id(), tuned.describe(), source
            )
            backend_name = tuned.backend
        require_float_path(key.rule, backend_name)
        if backend_name == "jax":
            return VmapEngine(key, capacity, chunk_steps)
        return HostBatchEngine(key, capacity, chunk_steps)
    backend_name = key.backend
    backend_kwargs: dict = {}
    if backend_name == "tuned":
        from tpu_life import autotune
        from tpu_life.runtime.metrics import log

        tk = autotune.tune_key_for(key.rule, key.shape)
        tuned, source = autotune.resolve(tk, mode="cache", shape=key.shape)
        log.info(
            "serve: autotune %s -> %s (%s)", tk.id(), tuned.describe(), source
        )
        backend_name = tuned.backend
        backend_kwargs = tuned.backend_kwargs()
    if backend_name == "jax":
        return VmapEngine(key, capacity, chunk_steps)
    if backend_name == "numpy":
        return HostBatchEngine(key, capacity, chunk_steps)
    from tpu_life.backends.base import get_backend

    return SlotLoopEngine(
        key,
        capacity,
        chunk_steps,
        get_backend(backend_name, rule=key.rule, **backend_kwargs),
    )
