"""Batched step engines: many tenant lattices through one compiled step.

The Ising-on-TPU lesson (PAPERS.md, arXiv:1903.11714) is that stencil
workloads only saturate an accelerator when many independent lattices ride
one compiled program.  An engine owns a **fixed-capacity padded batch**:
a ``(capacity, h, w)`` int8 array plus a per-slot ``remaining`` step
vector.  Continuous batching falls out of two properties:

- the compiled chunk function has *constant shapes* — capacity, board
  geometry and chunk length never vary — so sessions can join and leave
  between host-sync chunks with **zero recompilation** (the acceptance
  test asserts ``compile_count == 1`` across 20 staggered sessions);
- per-slot step budgets are enforced *inside* the compiled scan by a
  freeze mask (``remaining > 0``): every step, slots whose budget is spent
  keep their board unchanged.  One fused scan therefore advances each
  slot by exactly ``min(chunk_steps, remaining[slot])`` steps — uneven
  budgets with bit-identical results to independent sequential runs.

Three executors behind one interface, mirroring the Backend split:

- :class:`VmapEngine`  — ``jax`` backend: ``vmap`` of the XLA stencil step
  under one jit/scan, the device path;
- :class:`HostBatchEngine` — ``numpy`` backend: the ground-truth executor
  on the same batch layout;
- :class:`SlotLoopEngine` — any other backend (sharded / pallas / native /
  stripes): one ``Runner`` per slot via the existing ``make_runner`` seam,
  advanced slot by slot.  Slower, but keeps the whole backend matrix
  servable without new kernels.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_life.models.rules import Rule


@dataclass(frozen=True)
class CompileKey:
    """What must match for two sessions to share one compiled batch.

    Admission groups sessions by this key (scheduler.py); each key owns
    one engine, one compiled program, one set of slots.  ``Rule`` is a
    frozen hashable value, so the key is usable as a dict key directly.
    """

    rule: Rule
    shape: tuple[int, int]  # (height, width)
    dtype: str  # board element type ("int8" today)
    backend: str  # executor family ("jax" / "numpy" / "sharded" / ...)


def compile_key_for(rule: Rule, board: np.ndarray, backend: str) -> CompileKey:
    return CompileKey(
        rule=rule,
        shape=(int(board.shape[0]), int(board.shape[1])),
        dtype=str(board.dtype),
        backend=backend,
    )


class EngineBase:
    """Slot bookkeeping shared by every executor.

    ``compile_count`` counts builds of the batched step program — the
    expensive event continuous batching exists to avoid.  Tests assert it
    stays at 1 per engine no matter how many sessions churn through.
    """

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        self.key = key
        self.capacity = capacity
        self.chunk_steps = chunk_steps
        self.compile_count = 0
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._remaining = np.zeros(capacity, dtype=np.int64)

    # -- slot lifecycle ----------------------------------------------------
    def acquire(self) -> int | None:
        """Claim a free slot (None when the batch is full)."""
        return self._free.pop() if self._free else None

    def release(self, slot: int) -> None:
        """Return a slot to the pool; its lattice is dead weight until the
        next load (the freeze mask already ignores it: remaining == 0)."""
        self._remaining[slot] = 0
        self._clear_slot(slot)
        self._free.append(slot)

    def occupancy(self) -> int:
        return self.capacity - len(self._free)

    def load(
        self,
        slot: int,
        board: np.ndarray,
        steps: int,
        *,
        seed: int | None = None,
        temperature: float | None = None,
        start_step: int = 0,
    ) -> None:
        """Stage a session's lattice into ``slot`` with ``steps`` budget.

        ``seed``/``temperature``/``start_step`` are the stochastic-tier
        per-slot state (``tpu_life.mc.engine``); deterministic engines
        ignore them — submit-time validation already rejected any
        meaningless combination.
        """
        h, w = self.key.shape
        if board.shape != (h, w):
            raise ValueError(
                f"board shape {board.shape} does not match engine key {self.key.shape}"
            )
        self._remaining[slot] = steps
        self._load_slot(slot, np.asarray(board, np.int8), steps)

    def remaining(self, slot: int) -> int:
        return int(self._remaining[slot])

    # -- the batched chunk -------------------------------------------------
    def advance_chunk(self) -> dict[int, int]:
        """Advance every occupied slot by ``min(chunk_steps, remaining)``
        steps in one batched dispatch; returns {slot: steps_advanced}."""
        advanced = {
            s: min(self.chunk_steps, int(r))
            for s, r in enumerate(self._remaining)
            if r > 0
        }
        if advanced:
            self._advance_impl()
            self._remaining = np.maximum(self._remaining - self.chunk_steps, 0)
        return advanced

    # -- executor hooks ----------------------------------------------------
    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        raise NotImplementedError

    def _clear_slot(self, slot: int) -> None:
        raise NotImplementedError

    def _advance_impl(self) -> None:
        raise NotImplementedError

    def fetch(self, slot: int) -> np.ndarray:
        raise NotImplementedError


class VmapEngine(EngineBase):
    """The device path: one jitted ``lax.scan`` over the whole batch.

    The batch axis is a plain ``jax.vmap`` over the existing single-board
    stencil step (``ops.stencil.make_step``) — the same jaxpr every
    single-session backend runs, so bit-identity with ``driver.run`` is
    inherited, not re-proven.  Boards stay device-resident between chunks;
    slot loads go through one jitted dynamic-update program (slot index
    traced, so joining a running batch never triggers a retrace).
    """

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int):
        super().__init__(key, capacity, chunk_steps)
        import jax
        import jax.numpy as jnp

        h, w = key.shape
        self._jnp = jnp
        self._boards = jax.device_put(
            jnp.zeros((capacity, h, w), dtype=jnp.int8)
        )
        self._rem_dev = jax.device_put(jnp.zeros(capacity, dtype=jnp.int32))

        # slot writer: slot index and budget are traced scalars, so every
        # load/evict reuses one compiled program regardless of which slot
        def set_slot(boards, rem, slot, board, steps):
            return boards.at[slot].set(board), rem.at[slot].set(steps)

        self._set_slot = jax.jit(set_slot, donate_argnums=(0, 1))
        self._chunk = None  # built lazily on first advance

    def _build_chunk(self):
        import jax
        import jax.numpy as jnp

        from tpu_life import obs
        from tpu_life.ops.stencil import make_step

        # the build itself is cheap; the first advance pays the XLA
        # compile — the span marks the event so a serve trace shows which
        # round took the compilation hit for which key
        obs.instant(
            "serve.compile",
            rule=self.key.rule.name,
            shape=f"{self.key.shape[0]}x{self.key.shape[1]}",
            backend=self.key.backend,
        )
        step = jax.vmap(make_step(self.key.rule))
        length = self.chunk_steps

        def chunk(boards, rem):
            def body(carry, _):
                bs, r = carry
                stepped = step(bs)
                live = (r > 0)[:, None, None]
                bs = jnp.where(live, stepped, bs)
                return (bs, jnp.maximum(r - 1, 0)), None

            (boards, rem), _ = jax.lax.scan(
                body, (boards, rem), None, length=length
            )
            return boards, rem

        self.compile_count += 1
        return jax.jit(chunk, donate_argnums=(0, 1))

    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        jnp = self._jnp
        self._boards, self._rem_dev = self._set_slot(
            self._boards,
            self._rem_dev,
            jnp.int32(slot),
            jnp.asarray(board, jnp.int8),
            jnp.int32(steps),
        )

    def _clear_slot(self, slot: int) -> None:
        h, w = self.key.shape
        self._load_slot(slot, np.zeros((h, w), np.int8), 0)

    def _advance_impl(self) -> None:
        if self._chunk is None:
            self._chunk = self._build_chunk()
        self._boards, self._rem_dev = self._chunk(self._boards, self._rem_dev)

    def fetch(self, slot: int) -> np.ndarray:
        return np.asarray(self._boards[slot])


class HostBatchEngine(EngineBase):
    """The numpy executor on the same batch layout — the serving twin of
    ``NumpyBackend``, and the truth executor the equivalence tests pin
    the device engine against."""

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int):
        super().__init__(key, capacity, chunk_steps)
        h, w = key.shape
        self._boards = np.zeros((capacity, h, w), dtype=np.int8)

    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        self._boards[slot] = board

    def _clear_slot(self, slot: int) -> None:
        self._boards[slot] = 0

    def _advance_impl(self) -> None:
        from tpu_life.ops.reference import step_np

        rule = self.key.rule
        for slot, rem in enumerate(self._remaining):
            n = min(self.chunk_steps, int(rem))
            b = self._boards[slot]
            for _ in range(n):
                b = step_np(b, rule)
            self._boards[slot] = b

    def fetch(self, slot: int) -> np.ndarray:
        return self._boards[slot].copy()


class SlotLoopEngine(EngineBase):
    """Fallback for backends with no batch axis (sharded / pallas / native
    / stripes): one device-resident ``Runner`` per slot via the existing
    ``make_runner`` seam, advanced slot by slot each chunk.  Compilation
    is the backend's business (each runner compiles its own step), so
    ``compile_count`` stays 0 here by design."""

    def __init__(self, key: CompileKey, capacity: int, chunk_steps: int, backend):
        super().__init__(key, capacity, chunk_steps)
        self._backend = backend
        self._runners: dict[int, object] = {}

    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        from tpu_life.backends.base import make_runner

        self._runners[slot] = make_runner(self._backend, board, self.key.rule)

    def _clear_slot(self, slot: int) -> None:
        self._runners.pop(slot, None)

    def _advance_impl(self) -> None:
        for slot, rem in enumerate(self._remaining):
            n = min(self.chunk_steps, int(rem))
            if n > 0:
                self._runners[slot].advance(n)

    def fetch(self, slot: int) -> np.ndarray:
        return self._runners[slot].fetch()


def make_engine(key: CompileKey, capacity: int, chunk_steps: int) -> EngineBase:
    """Engine factory, dispatched on the key's executor family.

    ``backend == "tuned"`` resolves the executor through the autotune
    cache per CompileKey — **read path only** (cache hit or analytic cost
    model): serving latency must never pay measurement cost, so an
    untuned key degrades to the cost-model pick, it does not trigger a
    trial sweep.  Run ``tpu-life tune`` offline to populate the cache.
    """
    if getattr(key.rule, "stochastic", False):
        # stochastic keys dispatch to the MC executors (per-slot seed /
        # temperature / step-counter state); backends without the key
        # schedule are a typed rejection, never a silent fallback
        from tpu_life.mc.engine import make_mc_engine

        return make_mc_engine(key, capacity, chunk_steps)
    backend_name = key.backend
    backend_kwargs: dict = {}
    if backend_name == "tuned":
        from tpu_life import autotune
        from tpu_life.runtime.metrics import log

        tk = autotune.tune_key_for(key.rule, key.shape)
        tuned, source = autotune.resolve(tk, mode="cache", shape=key.shape)
        log.info(
            "serve: autotune %s -> %s (%s)", tk.id(), tuned.describe(), source
        )
        backend_name = tuned.backend
        backend_kwargs = tuned.backend_kwargs()
    if backend_name == "jax":
        return VmapEngine(key, capacity, chunk_steps)
    if backend_name == "numpy":
        return HostBatchEngine(key, capacity, chunk_steps)
    from tpu_life.backends.base import get_backend

    return SlotLoopEngine(
        key,
        capacity,
        chunk_steps,
        get_backend(backend_name, rule=key.rule, **backend_kwargs),
    )
