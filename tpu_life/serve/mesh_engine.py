"""tpu_life.serve.mesh_engine: the mega-board mesh engine tier.

Every engine before this one held a session's whole board on one chip;
the governor (docs/SERVING.md) turned "board bigger than one chip" into
a typed 413 instead of an OOM, and that was the ceiling.  This module
removes it: a :class:`MeshEngine` speaks the pump's ``dispatch_chunk`` /
``collect_chunk`` / ``settle`` contract (serve/engine.py) on top of the
sharded 2-D torus backend (backends/sharded_backend.py — ppermute halo
exchange on both mesh axes), so a session whose estimate says "never
fits" is *placed* on a reserved multi-device slice instead of rejected,
coexisting with batched small sessions on the remaining capacity (the
MPMD-coordinator shape of arXiv 2412.14374).

Key differences from the single-chip engines:

- **capacity is pinned to 1** — the mega-board owns its slice; batching
  is what the other engines are for.
- **compute is deferred** like :class:`SlotLoopEngine`: ``dispatch``
  records intent, ``collect`` runs the halo-exchange scan under a
  ``mesh.halo-exchange`` trace span.
- **durability is shard-wise**: :meth:`MeshEngine.spill_tiles` walks the
  runner's *addressable shards* and yields one logical-cell tile per
  shard — each host spills only its own bytes (serve/spill.py writes
  per-tile CRC sidecars plus a sharded manifest).  A resumed session
  re-enters through :meth:`MeshEngine.load_tiles`, where each
  destination shard pulls exactly its own cell rectangle from the tile
  set — onto a possibly *different* mesh shape (the memory-efficient
  redistribution of arXiv 2112.01075) — so the full board is never
  materialized on one host in either direction.
"""

from __future__ import annotations

import numpy as np

from tpu_life import obs
from tpu_life.models.rules import Rule
from tpu_life.serve.engine import EngineBase

__all__ = [
    "MeshEngine",
    "mesh_backend_name",
    "parse_mesh_backend",
    "plan_mesh_shape",
]


def mesh_backend_name(shape: tuple[int, int]) -> str:
    """The ``CompileKey.backend`` encoding of a mesh placement — e.g.
    ``"mesh:2x4"``.  Kept inside the key so engines (and the engine
    cache, and crash recovery) rebuild purely from the key."""
    r, c = shape
    return f"mesh:{int(r)}x{int(c)}"


def parse_mesh_backend(backend: str) -> tuple[int, int] | None:
    """``"mesh:RxC"`` -> ``(R, C)``; ``None`` for non-mesh backends."""
    if not str(backend).startswith("mesh:"):
        return None
    spec = str(backend)[len("mesh:") :]
    try:
        r_s, c_s = spec.split("x", 1)
        r, c = int(r_s), int(c_s)
    except ValueError:
        raise ValueError(f"malformed mesh backend {backend!r} (want mesh:RxC)")
    if r < 1 or c < 1 or r * c < 2:
        raise ValueError(f"mesh backend {backend!r} needs at least 2 devices")
    return (r, c)


def plan_mesh_shape(
    devices: int, shape: tuple[int, int], rule: Rule
) -> tuple[int, int] | None:
    """Deterministic mesh shape for ``devices`` chips over an ``h x w``
    board, or ``None`` when no legal factorization exists.

    Preference order: most-square factorization first (least halo
    perimeter per shard), rows-major on ties — the same instinct as the
    paper's stripe decomposition, generalized to 2-D.  A factorization
    is legal when every shard still spans at least one halo radius on
    each axis, and (torus boundary only) when the board divides exactly
    — the closed-ring scaffold cannot pad a wrapped axis.
    """
    h, w = int(shape[0]), int(shape[1])
    devices = int(devices)
    if devices < 2:
        return None
    cands = [(devices // c, c) for c in range(1, devices + 1) if devices % c == 0]
    cands.sort(key=lambda rc: (abs(rc[0] - rc[1]), -rc[0]))
    radius = max(1, int(getattr(rule, "radius", 1)))
    torus = getattr(rule, "boundary", "clamped") == "torus"
    for r, c in cands:
        if torus and (h % r or w % c):
            continue
        if h // r < radius or w // c < radius:
            continue
        return (r, c)
    return None


class MeshEngine(EngineBase):
    """A capacity-1 engine whose single board is sharded over a 2-D
    device mesh with ppermute halo exchange — the serving face of the
    paper's stripe decomposition.  Built entirely from its
    :class:`CompileKey` (backend ``mesh:RxC``), like every other engine,
    so crash recovery and the engine cache need no extra state."""

    def __init__(self, key, chunk_steps: int):
        from tpu_life.backends.sharded_backend import ShardedBackend

        if getattr(key.rule, "stochastic", False):
            raise ValueError(
                f"rule {key.rule.name!r} is stochastic: the mesh tier has no "
                "sharded Monte-Carlo path; submit at single-chip scale"
            )
        mesh_shape = parse_mesh_backend(key.backend)
        if mesh_shape is None:
            raise ValueError(f"MeshEngine needs a mesh:RxC backend, got {key.backend!r}")
        super().__init__(key, 1, chunk_steps)
        self.mesh_shape = mesh_shape
        stencil = self.stencil or "roll"
        self._backend = ShardedBackend(mesh_shape=mesh_shape, stencil=stencil)
        self._runners: dict[int, object] = {}

    # -- mesh identity ------------------------------------------------

    @property
    def devices(self) -> int:
        r, c = self.mesh_shape
        return r * c

    def _mesh_label(self) -> str:
        r, c = self.mesh_shape
        return f"{r}x{c}"

    # -- EngineBase hooks ---------------------------------------------

    def _load_slot(self, slot: int, board: np.ndarray, steps: int) -> None:
        self._runners[slot] = self._backend.prepare(board, self.key.rule)
        self.compile_count += 1

    def _clear_slot(self, slot: int) -> None:
        self._runners.pop(slot, None)

    def _dispatch_impl(self) -> None:
        # deferred, like SlotLoopEngine: the halo-exchange scan runs at
        # collect time so dispatch stays non-blocking for the pump
        pass

    def _collect_impl(self, advanced: dict[int, int]) -> None:
        for slot, steps in advanced.items():
            runner = self._runners.get(slot)
            if runner is None:
                continue
            with obs.span(
                "mesh.halo-exchange",
                mesh=self._mesh_label(),
                steps=int(steps),
                stencil=self.stencil or "roll",
            ):
                runner.advance(int(steps))
                runner.sync()

    def _peek_board(self, slot: int) -> np.ndarray:
        # a full-board gather: fine for result fetch / recovery salvage,
        # but the spill path goes through spill_tiles() instead
        return np.asarray(self._runners[slot].fetch())

    # -- shard-wise durability ----------------------------------------

    def spill_tiles(self, slot: int):
        """``(tiles, lag)`` where tiles is a list of ``(r0, c0, cells)``
        — one per addressable shard, padding stripped.  Never gathers
        the board: each host reads only its own shards' bytes."""
        if slot not in self._runners:
            raise KeyError(f"slot {slot} has no runner")
        lag = self._inflight.get(slot, 0)
        h, w = self.key.shape
        runner = self._runners[slot]
        tiles = list(
            self._backend.iter_runner_tiles(runner, h, w, self.key.rule)
        )
        return tiles, lag

    def load_tiles(self, slot: int, load_block, steps: int, *, start_step: int = 0) -> None:
        """The re-gather face of :meth:`spill_tiles`: occupy ``slot``
        from a rectangular block loader (``load_block(r0, r1, c0, c1)``)
        instead of a materialized board.  Each destination shard pulls
        its own rectangle — the tile set may have been written by a mesh
        of any other shape (arXiv 2112.01075)."""
        if slot in self._inflight or slot in self._lost:
            raise RuntimeError(f"slot {slot} is in flight; collect or salvage first")
        h, w = self.key.shape
        with obs.span(
            "mesh.regather",
            mesh=self._mesh_label(),
            height=int(h),
            width=int(w),
        ):
            self._runners[slot] = self._backend.prepare_from_blocks(
                load_block, h, w, self.key.rule
            )
        self.compile_count += 1
        self._remaining[slot] = int(steps)
