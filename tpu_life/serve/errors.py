"""Typed serving errors — the contract boundary of ``tpu_life.serve``.

The reference program has exactly one failure mode: the process dies.  A
serving layer needs *typed* rejections a caller can branch on: a full
queue is backpressure (retry later, shed load upstream), a bad board is a
client error (never retry), an unknown session id is a protocol bug.
Everything subclasses :class:`ServeError` so front-ends can catch the
whole family in one clause while tests assert the precise type.
"""

from __future__ import annotations


class ServeError(Exception):
    """Base class for every error the serving layer raises on purpose."""


class QueueFull(ServeError):
    """Backpressure: the admission queue is at capacity.

    Raised by ``submit`` *synchronously* — the request was never stored, so
    rejecting it bounds memory.  The caller should retry after draining or
    shed the request upstream.
    """


class Draining(ServeError):
    """Admission is closed: the service is draining toward shutdown.

    Raised by ``submit`` after :meth:`SimulationService.begin_drain` —
    in-flight sessions keep running to completion, but no new work is
    accepted.  Front-ends map this to 503 + ``Retry-After`` so a
    load-balanced client retries against a peer that is still admitting.
    """


class InsufficientMemory(ServeError):
    """Admission-time memory governance (docs/SERVING.md "Resource
    governance"): admitting this session's CompileKey would push the
    estimated engine footprint past ``ServeConfig.memory_budget_bytes``.

    Raised by ``submit`` *synchronously* — nothing is stored, so an XLA
    ``RESOURCE_EXHAUSTED`` at engine build time becomes a rejected
    request instead of a dead worker.  ``transient`` is the retry
    contract: True means the key would fit on an otherwise-idle service
    (other keys' engines are holding the budget — retry after they
    drain, HTTP 503 + Retry-After); False means this single session's
    engine alone can never fit the budget (HTTP 413, never retried).
    ``estimated_bytes`` / ``budget_bytes`` carry the arithmetic so
    clients and tests can see exactly what was refused.

    A permanent (413) rejection additionally carries the mesh hint
    (docs/SERVING.md "Mega-board sessions"): ``mesh_eligible`` is True
    when the board has a sharded path (deterministic or continuous) and
    a multi-device slice could hold it, and ``min_devices`` is the
    smallest such slice — so clients and the fleet router can
    distinguish "resubmit to a mesh-capable worker" from "hopeless".
    """

    def __init__(
        self,
        message: str,
        *,
        transient: bool,
        estimated_bytes: int,
        budget_bytes: int,
        mesh_eligible: bool = False,
        min_devices: int | None = None,
    ):
        super().__init__(message)
        self.transient = transient
        self.estimated_bytes = estimated_bytes
        self.budget_bytes = budget_bytes
        self.mesh_eligible = mesh_eligible
        self.min_devices = min_devices


class QuotaExceeded(ServeError):
    """Tenant QoS (docs/SERVING.md "Tenant QoS"): admitting this request
    would push its tenant past a declared quota.

    Raised by ``submit`` (``max_sessions`` / ``memory_fraction``) and
    ``stream_subscribe`` (``max_watchers``) *synchronously* — nothing is
    stored, exactly the QueueFull discipline.  Front-ends map it to 429
    ``quota_exceeded`` with Retry-After: the tenant's own earlier work
    must finish before more admits, so the wait is real, not overload.
    ``tenant`` / ``quota`` / ``limit`` carry the arithmetic for clients
    branching beyond the code.
    """

    def __init__(self, message: str, *, tenant: str, quota: str, limit):
        super().__init__(message)
        self.tenant = tenant
        self.quota = quota
        self.limit = limit


class SessionTimeout(ServeError):
    """A session exceeded its per-request deadline.

    Never raised to the submitter directly; recorded as the FAILED
    session's ``error`` so ``poll`` can report it (the submitter may long
    since have gone away — the timeout exists to reclaim its slot).
    """


class UnknownSession(ServeError):
    """``poll``/``cancel``/``result`` named a session id that was never
    issued by this service instance."""


class SessionFailed(ServeError):
    """Raised by ``result`` when the session terminated without a board
    (FAILED or CANCELLED); carries the session's recorded error string."""
