"""The two-control-plane chaos drill: failure masking across a host
boundary, machine-verified.

``run_cross_host_drill`` stands up the full cross-host topology on one
machine (docs/FLEET.md "Cross-host topology") and breaks it on a seeded
schedule:

- one **shared remote spill store** (:class:`SpillHTTPServer`) both
  control planes read and write through — the only channel spilled bytes
  cross the "host" boundary by;
- **control plane A**: a supervisor/router with ZERO locally-spawned
  workers and site prefix ``a-``, whose only capacity is a
  **wire-registered** gateway worker (a real ``tpu-life gateway
  --register`` subprocess holding a heartbeat-renewed lease);
- **control plane B**: a second supervisor/router with its own disjoint
  local workers and site prefix ``b-``, named as A's **peer**.

The seeded faults, all live at once in one run:

- ``lease.heartbeat.drop`` silences the registered worker's first beats:
  its lease expires, A fences the generation and migrates its sessions —
  and with no local survivors, every rescue crosses to PEER B, read out
  of the shared store.  When a later beat finally lands, the worker is
  refused with the typed 410 ``lease_expired`` (never re-admitted over
  its rescued sessions), drops its local copies, and re-registers fresh;
- ``lease.register.reset`` tears the first registration POST (the
  handshake must be retry-idempotent);
- ``spill.remote.timeout`` / ``spill.remote.torn_body`` break the wire
  spill path in both directions (write degrades one session to
  ``spill_disabled``; a torn read demotes to the predecessor snapshot);
- ``net.partition`` severs seeded per-pair links (router->worker,
  worker->store, registrar->control-plane);
- a drill-driven **SIGKILL** lands on the B worker that adopted rescued
  sessions, so the adopted work survives a SECOND death (the
  spill-on-adopt contract) via B's own remote-store migration.

The PR 10 invariant set is then verified across the boundary:
``all_terminal`` / ``bit_identity`` / ``legal_410`` / ``no_lost_work`` /
``recovery_bounded`` / ``metrics_consistent`` (routed == accepted at A),
plus the cross-host-specific ``fencing`` invariant (the lease expired,
the reconnect was refused typed, the worker observed the fence and
re-registered).  Every summary carries the seed + plan digest that
replay the schedule verbatim.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass
from pathlib import Path

from tpu_life import chaos
from tpu_life.chaos.drill import _Driller
from tpu_life.fleet.registry import parse_fleet_sid
from tpu_life.gateway.client import GatewayClient
from tpu_life.runtime.metrics import log

#: The default cross-host fault mix: every family bounded (``times``) so
#: each seam fires AND heals inside one run — the drill's assertions need
#: "did it fire?" to be a deterministic question.
DEFAULT_CROSS_POINTS: dict[str, dict] = {
    # silence the registered worker's first five heartbeats: with the
    # drill's lease TTL the lease expires mid-run (sessions aboard),
    # and the sixth beat meets the fence
    "lease.heartbeat.drop": {"rate": 1.0, "mode": "drop", "times": 5},
    # the first registration POST is torn pre-send: the handshake retries
    "lease.register.reset": {"rate": 1.0, "mode": "reset", "times": 1},
    # one remote spill write times out per worker process (that session
    # degrades to spill_disabled); one downloaded snapshot body is torn
    # (the read demotes to its predecessor)
    "spill.remote.timeout": {"rate": 1.0, "mode": "timeout", "times": 1},
    "spill.remote.torn_body": {"rate": 1.0, "mode": "torn", "times": 1},
    # the first two consulted links per process sever, then heal
    "net.partition": {"rate": 1.0, "mode": "drop", "times": 2},
}


@dataclass
class CrossHostConfig:
    seed: int = 0
    #: local workers under control plane B (A has only the registered one)
    workers: int = 2
    det_sessions: int = 4
    ising_sessions: int = 1
    size: int = 16
    steps: int = 600
    kills: int = 1
    min_progress: int = 4
    points: dict | None = None
    backend: str = "numpy"
    capacity: int = 8
    chunk_steps: int = 2
    spill_every: int = 1
    lease_ttl_s: float = 8.0
    resubmit_lost: int = 3
    recovery_bound_s: float = 90.0
    wait_timeout_s: float = 240.0
    migrate_stuck_after_s: float = 60.0
    workdir: str = "."
    summary_file: str | None = None


def _pkg_env() -> dict:
    env = dict(os.environ)
    pkg_root = str(Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = (
        pkg_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else pkg_root
    )
    return env


def run_cross_host_drill(cfg: CrossHostConfig) -> dict:
    """Run one seeded two-control-plane drill; returns the summary record
    (also appended to ``cfg.summary_file`` when set).  ``summary["ok"]``
    is the single verdict; on failure the summary names the seed and plan
    digest that replay the run verbatim."""
    from tpu_life.fleet import Fleet, FleetConfig
    from tpu_life.serve.spill_http import SpillHTTPServer

    if cfg.kills != 1:
        # the choreography is scripted — gateway SIGKILL, peer rescue,
        # then exactly ONE adopter SIGKILL on B (the spill-on-adopt
        # proof); a summary stamped with a kill count the drill never
        # performed would break the verbatim-replay contract
        raise chaos.ChaosError(
            f"the cross-host drill performs exactly one adopter SIGKILL "
            f"(--kills must be 1, got {cfg.kills}); --kills N is the "
            f"single-plane drill's knob"
        )
    points = DEFAULT_CROSS_POINTS if cfg.points is None else cfg.points
    # the driller shim: reuse the single-plane drill's workload builder,
    # client loop, oracle byte-checks and violation ledger verbatim
    d = _Driller(_shim(cfg, points))
    spec = d.plan.spec()
    t_start = time.monotonic()
    prev_env = os.environ.get(chaos.ENV_VAR)
    os.environ[chaos.ENV_VAR] = json.dumps(spec)  # subprocesses inherit
    chaos.arm(d.plan)  # this process: both routers/supervisors/migrators
    workdir = Path(cfg.workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    evidence: dict = {}

    store = SpillHTTPServer(str(workdir / "store"))
    store.start()
    worker_args = (
        "--serve-backend", cfg.backend,
        "--capacity", str(cfg.capacity),
        "--chunk-steps", str(cfg.chunk_steps),
        "--max-queue", str(4 * (cfg.det_sessions + cfg.ising_sessions)),
    )
    common = dict(
        port=0,
        worker_args=worker_args,
        spill_url=store.url,
        spill_every=cfg.spill_every,
        lease_ttl_s=cfg.lease_ttl_s,
        probe_interval_s=0.1,
        backoff_base_s=0.2,
        migrate_stuck_after_s=cfg.migrate_stuck_after_s,
    )
    fleet_b = Fleet(FleetConfig(
        workers=cfg.workers,
        site="b-",
        log_dir=str(workdir / "logs-b"),
        **common,
    ))
    proc = None
    worker_log = workdir / "registered-worker.log"
    try:
        fleet_b.start()
        fleet_a = Fleet(FleetConfig(
            workers=0,
            site="a-",
            peers=(f"http://127.0.0.1:{fleet_b.port}",),
            log_dir=str(workdir / "logs-a"),
            **common,
        ))
        d.fleet = fleet_a
        try:
            fleet_a.start()
            a_url = f"http://127.0.0.1:{fleet_a.port}"
            d.base_url = a_url
            # the wire-registered worker: control plane A's ONLY capacity.
            # Registration (not spawning) is how it joins; the startup
            # JSON contract is the handshake.
            argv = [
                sys.executable, "-m", "tpu_life", "gateway",
                "--host", "127.0.0.1", "--port", "0",
                "--spill-url", store.url,
                "--register", a_url,
                *worker_args,
                "--spill-every", str(cfg.spill_every),
            ]
            with open(worker_log, "ab") as logf:
                proc = subprocess.Popen(
                    argv,
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                    env=_pkg_env(),
                    start_new_session=True,
                )
            if not fleet_b.wait_ready(timeout=120, min_workers=cfg.workers):
                raise RuntimeError(
                    f"fleet B never ready: {fleet_b.supervisor.states()}"
                )
            if not fleet_a.wait_ready(timeout=120, min_workers=1):
                raise RuntimeError(
                    f"registered worker never joined A: "
                    f"{fleet_a.supervisor.states()}"
                )
            client = GatewayClient(a_url, retries=10)
            for item in d.items:
                d.submit_item(client, item)
            _drive_faults(cfg, d, fleet_a, fleet_b, evidence)
            _settle_items(cfg, d, client, fleet_a)
            d._scrape_injections()
            d.base_url = f"http://127.0.0.1:{fleet_b.port}"
            d._scrape_injections()  # B's workers' counters too
            d.base_url = a_url
            _check_books(cfg, d, fleet_a, fleet_b, evidence)
        finally:
            try:
                fleet_a.begin_drain()
                fleet_a.wait(timeout=60)
            finally:
                fleet_a.close()
    finally:
        try:
            fleet_b.begin_drain()
            fleet_b.wait(timeout=60)
        finally:
            fleet_b.close()
            if proc is not None:
                _stop_worker(proc, d, evidence, worker_log)
            store.close()
            chaos.disarm()
            if prev_env is None:
                os.environ.pop(chaos.ENV_VAR, None)
            else:
                os.environ[chaos.ENV_VAR] = prev_env
    elapsed = time.monotonic() - t_start
    verdicts = d.verdicts()
    verdicts["fencing"] = {
        "ok": not d.violations.get("fencing"),
        "violations": d.violations.get("fencing", []),
    }
    outcomes: dict[str, int] = {}
    for item in d.items:
        outcomes[item.outcome] = outcomes.get(item.outcome, 0) + 1
    done = outcomes.get("done", 0)
    summary = {
        "kind": "cross_host_drill",
        # the replay stamp: seed + canonical plan + digest — a failed CI
        # drill reruns locally from exactly these
        "seed": cfg.seed,
        "plan": spec,
        "plan_digest": d.plan.digest(),
        "workers_b": cfg.workers,
        "lease_ttl_s": cfg.lease_ttl_s,
        "kills": d.kills,
        "sessions": len(d.items),
        "accepted": d.accepted,
        "outcomes": outcomes,
        "resubmits": sum(i.resubmits for i in d.items),
        "delivered": sum(1 for i in d.items if i.delivered),
        "injections": _merged_injections(d),
        "lease": evidence.get("lease", {}),
        "peer_rescues": evidence.get("peer_rescues", 0),
        "registrar": evidence.get("registrar"),
        "invariants": verdicts,
        "ok": all(v["ok"] for v in verdicts.values()),
        "elapsed_s": elapsed,
        "sessions_per_sec": done / elapsed if elapsed > 0 else 0.0,
    }
    if cfg.summary_file:
        from tpu_life import obs

        obs.ensure_parent(cfg.summary_file)
        with open(cfg.summary_file, "a") as f:
            f.write(json.dumps(summary) + "\n")
    return summary


def _shim(cfg: CrossHostConfig, points: dict):
    """A DrillConfig-shaped view of the cross-host config, so
    :class:`_Driller`'s workload/oracle/poll machinery is reused as-is."""
    from tpu_life.chaos.drill import DrillConfig

    return DrillConfig(
        seed=cfg.seed,
        workers=cfg.workers,
        det_sessions=cfg.det_sessions,
        ising_sessions=cfg.ising_sessions,
        size=cfg.size,
        steps=cfg.steps,
        kills=cfg.kills,
        min_progress=cfg.min_progress,
        points=points,
        backend=cfg.backend,
        capacity=cfg.capacity,
        chunk_steps=cfg.chunk_steps,
        spill_every=cfg.spill_every,
        resubmit_lost=cfg.resubmit_lost,
        recovery_bound_s=cfg.recovery_bound_s,
        wait_timeout_s=cfg.wait_timeout_s,
        migrate_stuck_after_s=cfg.migrate_stuck_after_s,
        workdir=cfg.workdir,
    )


def _drive_faults(cfg, d, fleet_a, fleet_b, evidence: dict) -> None:
    """The fault choreography: wait out the chaos-driven lease expiry,
    observe the cross-host rescue, then SIGKILL the adopter on B."""
    sup_a = fleet_a.supervisor
    # 1. the heartbeat-loss expiry (chaos-driven): bounded wait
    deadline = time.monotonic() + cfg.lease_ttl_s * 3 + 60
    while sup_a._c_lease_expired.value < 1:
        if time.monotonic() > deadline:
            d.violate(
                "recovery_bounded",
                "the registered worker's lease never expired (heartbeat "
                "drops should have silenced it)",
            )
            return
        time.sleep(0.1)
    log.info("cross-host drill: lease expired — fence + migration underway")
    # 2. rescues cross to peer B (A has no local survivors until the
    # worker re-registers).  Not every session necessarily crosses — a
    # spill-degraded one is typed-lost instead — but at least one must.
    owners: set[str] = set()
    deadline = time.monotonic() + cfg.wait_timeout_s
    while time.monotonic() < deadline:
        pins = [
            fleet_a.migrator.peer_of(item.sid)
            for item in d.items
            if item.sid is not None
        ]
        owners = {
            pin.worker
            for p in pins
            if p is not None and (pin := parse_fleet_sid(p[1])) is not None
        }
        if owners or fleet_a.migrator.wait_idle(timeout=0.01):
            break
        time.sleep(0.1)
    evidence["peer_rescues"] = sum(
        1
        for item in d.items
        if item.sid is not None and fleet_a.migrator.peer_of(item.sid)
    )
    if not owners:
        d.violate(
            "no_lost_work",
            "no session was rescued onto the peer control plane",
        )
        return
    # 3. SIGKILL the B worker that adopted rescued sessions (seeded pick
    # among adopters): the second death — adopted work must survive it
    # through B's own remote-store migration (spill-on-adopt).
    ready_b = {w.name: w for w in fleet_b.supervisor.ready_workers()}
    candidates = sorted(n for n in owners if n in ready_b)
    if not candidates:
        d.violate("recovery_bounded", "no live adopter to SIGKILL on B")
        return
    victim = ready_b[candidates[d._draw("crosshost.kill", 0) % len(candidates)]]
    gen0 = victim.generation
    t0 = time.monotonic()
    os.kill(victim.proc.pid, signal.SIGKILL)
    log.info("cross-host drill: SIGKILL %s on control plane B", victim.name)
    deadline = t0 + cfg.recovery_bound_s
    while not (
        victim.generation > gen0
        and len(fleet_b.supervisor.ready_workers()) >= cfg.workers
    ):
        if time.monotonic() > deadline:
            d.kills.append({"worker": f"B/{victim.name}", "recovery_s": None})
            d.violate(
                "recovery_bounded",
                f"B not back to {cfg.workers} ready within "
                f"{cfg.recovery_bound_s:.0f}s of the SIGKILL",
            )
            return
        time.sleep(0.05)
    d.kills.append(
        {"worker": f"B/{victim.name}", "recovery_s": time.monotonic() - t0}
    )


def _settle_items(cfg, d, client, fleet_a) -> None:
    """Poll everything to terminal through A (original sids; rescued ones
    proxy to B), playing the documented resubmit recourse for typed
    losses — but only once A has capacity again (the re-registered
    worker), so the recourse is not wasted on a still-empty plane."""
    for item in d.items:
        if item.sid is None:
            continue
        d.poll_until_terminal(client, item)
    needs_recourse = any(
        i.outcome in ("lost", "failed") for i in d.items if i.sid is not None
    )
    if needs_recourse:
        deadline = time.monotonic() + cfg.recovery_bound_s
        while not fleet_a.supervisor.ready_workers():
            if time.monotonic() > deadline:
                break  # the resubmit itself will surface the violation
            time.sleep(0.1)
    for item in d.items:
        if item.sid is None:
            continue
        while (
            item.outcome in ("lost", "failed")
            and item.resubmits < cfg.resubmit_lost
        ):
            item.resubmits += 1
            if not d.submit_item(client, item):
                break
            d.poll_until_terminal(client, item)
    for item in d.items:
        if not item.delivered:
            d.violate(
                "no_lost_work",
                f"{item.tag} never yielded its oracle board "
                f"(final: {item.outcome} {item.detail})",
            )


def _check_books(cfg, d, fleet_a, fleet_b, evidence: dict) -> None:
    """The cross-host accounting: routed==accepted at A, the lease/fence
    evidence, the peer-rescue counter, and the injection floor."""
    stats = fleet_a.stats()
    routed = sum(stats.get("routed", {}).values())
    if routed != d.accepted:
        d.violate(
            "metrics_consistent",
            f"A's fleet_routed_total {routed} != accepted 201s {d.accepted}",
        )
    if any(i.outcome == "pending" for i in d.items):
        d.violate(
            "metrics_consistent", "an item finished the drill still pending"
        )
    sup_a = fleet_a.supervisor
    lease = {
        "expired": sup_a._c_lease_expired.value,
        "refused": sup_a._c_lease_refused.value,
        "registrations": sup_a._c_registrations.value,
    }
    evidence["lease"] = lease
    # the fence invariant: the lease expired, the stale generation is
    # terminally fenced, the reconnect was refused typed (the refusal
    # counter only moves on the 410 path), and a FRESH generation was
    # admitted — never the old one back
    if lease["expired"] < 1:
        d.violate("fencing", "no lease ever expired")
    if lease["refused"] < 1:
        d.violate(
            "fencing",
            "no fenced heartbeat was ever refused (the worker was never "
            "told, typed, that it lost its lease)",
        )
    if lease["registrations"] < 2:
        d.violate(
            "fencing",
            f"the fenced worker never re-registered "
            f"(registrations={lease['registrations']})",
        )
    if not sup_a.is_fenced("w0", 1):
        d.violate("fencing", "the first registered generation is not fenced")
    mig_a = stats.get("migrations", {})
    evidence["migrations_a"] = mig_a
    evidence["migrations_b"] = fleet_b.stats().get("migrations", {})
    if evidence.get("peer_rescues", 0) < 1 and mig_a.get("peer", 0) < 1:
        d.violate(
            "no_lost_work", "no cross-host (peer) rescue was ever recorded"
        )


def _merged_injections(d) -> dict[str, float]:
    """Injection evidence from every vantage: this process's live
    counters, both fleets' merged scrapes (which carry the supervisors'
    per-worker retention — exact across deaths), summed per point."""
    merged: dict[str, float] = {}
    for point, outcomes in chaos.counts().items():
        merged[point] = merged.get(point, 0.0) + float(sum(outcomes.values()))
    for point, total in d.injections_by_point().items():
        # the scraped view already includes this process's counters for
        # fleet-side points (the merged /metrics carries them); keep the
        # larger of the two vantages per point rather than double-adding
        merged[point] = max(merged.get(point, 0.0), total)
    return merged


def _stop_worker(proc, d, evidence: dict, worker_log: Path) -> None:
    """SIGTERM the registered worker, reap it, and read its summary line
    back for the worker-side fence evidence."""
    try:
        proc.terminate()
        proc.wait(timeout=30)
    except Exception:
        proc.kill()
        try:
            proc.wait(timeout=10)
        except Exception:
            log.warning("cross-host drill: registered worker unkillable")
    registrar = None
    try:
        for raw in worker_log.read_bytes().splitlines():
            raw = raw.strip()
            if not raw.startswith(b"{"):
                continue
            try:
                doc = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if isinstance(doc.get("registrar"), dict):
                registrar = doc["registrar"]
    except OSError:
        pass
    evidence["registrar"] = registrar
    if registrar is None:
        d.violate(
            "fencing", "the registered worker left no registrar summary"
        )
    else:
        if registrar.get("fenced", 0) < 1:
            d.violate(
                "fencing",
                "the worker never observed a typed lease_expired fence "
                f"(registrar={registrar})",
            )
        if registrar.get("registrations", 0) < 2:
            d.violate(
                "fencing",
                f"the worker never re-registered after the fence "
                f"(registrar={registrar})",
            )
