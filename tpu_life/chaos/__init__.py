"""tpu_life.chaos — deterministic fault injection for the serving fleet.

PRs 5-9 built a fault-tolerance stack (spill-backed failover, a
migrator, breakers, refusal-only retry); this package turns "robust"
from an anecdote into a seeded, replayable, machine-checked property.
Two pieces:

- the **injection registry** (this module): a process-wide, seeded
  :class:`ChaosPlan` of named injection points threaded through the real
  seams — spill writes/reads, snapshot bytes, worker pump loops, router
  sockets, engine chunk dispatch/collect, the supervisor's probe clock,
  the migrator thread.  Every decision is a **pure function of (seed,
  point, nth call at that point)** — the same Threefry-2x32 counter
  discipline as ``tpu_life.mc.prng`` — so a chaos run's fault schedule
  replays exactly from its seed.  Disarmed (the default), every seam is
  a no-op: one module-global ``None`` check, no draws, no counting —
  asserted suite-wide by the conftest guard via :func:`injection_count`.
- the **drill runner** (:mod:`tpu_life.chaos.drill`, ``tpu-life
  chaos``): drives a real N-worker CPU fleet under a seeded fault
  schedule plus drill-driven SIGKILLs while a det+ising workload flows
  through the unmodified client, then checks machine-verified
  invariants (docs/CHAOS.md).

Arming: programmatic (``chaos.arm(plan)`` / the :func:`armed_plan`
context manager) or via ``TPU_LIFE_CHAOS`` — a JSON plan spec in the
environment, picked up once at CLI entry (``maybe_arm_from_env``), which
is how the drill arms the gateway *worker subprocesses* it spawns: the
supervisor's spawn copies the parent environment, so one exported spec
arms every process of the fleet, each drawing its own per-process
deterministic schedule.

Plan spec (JSON)::

    {"seed": 42,
     "points": {"spill.write":  {"rate": 1.0, "mode": "enospc", "times": 2},
                "worker.crash": {"rate": 0.02, "mode": "exit"}}}

``rate`` is the per-call fire probability (the Threefry draw decides),
``mode`` selects the failure shape at that seam, optional ``times``
bounds total fires (the first ``times`` firing draws fire, later ones
pass — a deterministic way to guarantee "exactly a couple of faults"),
and mode-specific knobs (``seconds`` for sleeps/skews) ride alongside.
Unknown points and modes are typed :class:`ChaosError`\\ s at plan
construction, never silent no-ops.
"""

from __future__ import annotations

import contextlib
import errno
import hashlib
import json
import os
import threading
import zlib
from dataclasses import dataclass

import numpy as _np

from tpu_life.mc.prng import key_halves, threefry2x32, threshold_u32
from tpu_life.obs import flight as _flight
from tpu_life.obs import trace as _trace

#: Environment variable carrying a JSON plan spec; read once per process
#: at CLI entry (``maybe_arm_from_env``), inherited by spawned workers.
ENV_VAR = "TPU_LIFE_CHAOS"

#: The injection-point table: name -> legal modes (docs/CHAOS.md has the
#: seam and failure-shape of each).  A closed set — arming an unknown
#: point is a typed error, so a typo'd drill never silently tests nothing.
POINTS: dict[str, tuple[str, ...]] = {
    # serve spill store (durability)
    "spill.write": ("enospc", "oserror"),  # raises inside SpillStore.save
    "spill.read": ("oserror",),  # raises inside read_spill_sessions
    "snapshot.corrupt": ("bitflip", "truncate"),  # mangles published bytes
    # serve engines (per-key chunk faults)
    "engine.dispatch": ("fault",),  # recovery.InjectedFault at dispatch
    "engine.collect": ("fault",),  # recovery.InjectedFault at collect
    # serve-tier resource governor (docs/SERVING.md "Resource governance")
    "engine.oom": ("oom",),  # RESOURCE_EXHAUSTED InjectedFault at dispatch
    "engine.wedge": ("sleep",),  # collect/settle stalls `seconds` (watchdog drill)
    # gateway worker lifecycle
    "worker.crash": ("exit",),  # os._exit from the pump loop
    "worker.hang": ("sleep",),  # pump loop stalls `seconds`
    "worker.unready": ("refuse",),  # /readyz answers 500
    "worker.start_delay": ("sleep",),  # startup line delayed `seconds`
    # fleet router transport
    "router.submit.reset": ("reset",),  # pre-send reset (refusal path)
    "router.poll.reset": ("mid_exchange", "mid_body"),  # ambiguity paths
    # live-session streaming (docs/STREAMING.md)
    "stream.reset": ("reset",),  # worker stream drops MID-FRAME (torn line)
    "watch.slow_reader": ("sleep",),  # a fan-out watcher stalls `seconds`
    # fleet supervisor / migrator
    "probe.skew": ("skew",),  # monitor clock reads skew by up to `seconds`
    "migrate.die": ("die",),  # the migration thread is never started
    # demand-driven autoscaling (docs/FLEET.md "Autoscaling")
    "scale.recruit.fail": ("refuse",),  # recruit() launches nobody (standby
    # failed to start) — the loop holds and retries next evaluation
    "scale.release.race": ("race",),  # scale-down victim selection grabs a
    # BUSY worker: the drain races live load; graceful release must
    # still lose no accepted session
    # cross-host control plane (docs/FLEET.md "Cross-host topology")
    "lease.heartbeat.drop": ("drop",),  # registrar heartbeat never sent
    "lease.register.reset": ("reset",),  # registration POST reset pre-send
    # remote spill store (HTTP backend)
    "spill.remote.timeout": ("timeout",),  # request times out client-side
    "spill.remote.torn_body": ("torn",),  # response body truncated on read
    # seeded per-peer connectivity mask: drawn PER PAIR via decide_pair,
    # so one armed point partitions some links and spares others — the
    # asymmetric-partition drill (router->worker, worker->control-plane,
    # worker->spill-store all consult it with their own pair labels)
    "net.partition": ("drop",),
}


class ChaosError(ValueError):
    """A malformed chaos plan (unknown point, unknown mode, bad rate) —
    typed so a drill config error fails loudly at construction."""


@dataclass(frozen=True)
class Fault:
    """One armed injection point's failure shape."""

    point: str
    mode: str
    rate: float = 1.0
    times: int | None = None  # bound on total fires (None = unlimited)
    seconds: float = 1.0  # sleep/skew magnitude for the timing modes

    def __post_init__(self):
        modes = POINTS.get(self.point)
        if modes is None:
            raise ChaosError(
                f"unknown chaos point {self.point!r} "
                f"(known: {', '.join(sorted(POINTS))})"
            )
        if self.mode not in modes:
            raise ChaosError(
                f"point {self.point!r} has no mode {self.mode!r} "
                f"(legal: {', '.join(modes)})"
            )
        if not 0.0 <= self.rate <= 1.0:
            raise ChaosError(f"rate must be in [0, 1], got {self.rate}")
        if self.times is not None and self.times < 0:
            raise ChaosError(f"times must be >= 0, got {self.times}")
        if self.seconds < 0:
            raise ChaosError(f"seconds must be >= 0, got {self.seconds}")


@dataclass
class Decision:
    """One fired injection: the fault plus its deterministic draw word
    (callers use ``draw`` for sub-choices — e.g. which bit to flip — so
    even the fault's *content* replays from the seed)."""

    fault: Fault
    draw: int  # the second Threefry output word, uint32


class ChaosPlan:
    """A seeded fault plan: per-point decisions as pure functions.

    The decision for the nth call at point ``p`` under seed ``S`` is::

        u0, u1 = threefry2x32(key=key_halves(S), counter=(crc32(p), n))
        fires  = u0 < threshold(rate)   (and fire_count < times)

    Per-point call counters are process-local, so every process in a
    fleet (router front, each worker) draws its own deterministic
    schedule from the one exported spec.  ``Decision.draw`` hands the
    second output word to the seam for deterministic sub-choices.
    """

    def __init__(self, seed: int, points: dict[str, dict] | None = None):
        self.seed = int(seed)
        self._k0, self._k1 = key_halves(self.seed)
        self.faults: dict[str, Fault] = {}
        for name, spec in (points or {}).items():
            if not isinstance(spec, dict):
                raise ChaosError(
                    f"point {name!r} spec must be an object, got {spec!r}"
                )
            unknown = set(spec) - {"rate", "mode", "times", "seconds"}
            if unknown:
                raise ChaosError(
                    f"point {name!r} spec has unknown keys {sorted(unknown)}"
                )
            if "mode" not in spec:
                raise ChaosError(f"point {name!r} spec needs a mode")
            self.faults[name] = Fault(
                point=name,
                mode=str(spec["mode"]),
                rate=float(spec.get("rate", 1.0)),
                times=None if spec.get("times") is None else int(spec["times"]),
                seconds=float(spec.get("seconds", 1.0)),
            )
        self._lock = threading.Lock()
        self._calls: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        # per-(point, pair) call counters for decide_pair — each network
        # link draws its own deterministic schedule
        self._pair_calls: dict[tuple[str, str], int] = {}

    @classmethod
    def from_spec(cls, spec: dict | str) -> "ChaosPlan":
        """Build from the JSON plan spec (dict, or its serialized form —
        the ``TPU_LIFE_CHAOS`` payload)."""
        if isinstance(spec, str):
            try:
                spec = json.loads(spec)
            except json.JSONDecodeError as e:
                raise ChaosError(f"chaos spec is not valid JSON: {e}") from None
        if not isinstance(spec, dict):
            raise ChaosError(f"chaos spec must be an object, got {spec!r}")
        unknown = set(spec) - {"seed", "points"}
        if unknown:
            raise ChaosError(f"chaos spec has unknown keys {sorted(unknown)}")
        return cls(int(spec.get("seed", 0)), spec.get("points") or {})

    def spec(self) -> dict:
        """The canonical JSON-able spec (round-trips through from_spec)."""
        points = {}
        for name, f in sorted(self.faults.items()):
            p: dict = {"rate": f.rate, "mode": f.mode}
            if f.times is not None:
                p["times"] = f.times
            if f.seconds != 1.0:
                p["seconds"] = f.seconds
            points[name] = p
        return {"seed": self.seed, "points": points}

    def digest(self) -> str:
        """A short stable digest of the canonical spec — stamped into
        drill summaries and BENCH_chaos records next to the seed, so a
        robustness number names exactly the adversity it survived."""
        blob = json.dumps(self.spec(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def _hit(self, fault: Fault, word: int, n: int) -> tuple[bool, int]:
        """ONE pure threefry draw for call ``n`` under counter ``word``:
        ``(fired?, payload draw)``.  The single place the fire predicate
        lives — the live deciders and the preview schedules share it, so
        they can never desynchronize."""
        u0, u1 = threefry2x32(
            _np, self._k0, self._k1, _np.uint32(word), _np.uint32(n)
        )
        hit = fault.rate >= 1.0 or int(u0) < threshold_u32(fault.rate)
        return hit, int(u1)

    def _decide(
        self, point: str, word: int, counts: dict, ckey
    ) -> Decision | None:
        fault = self.faults.get(point)
        if fault is None:
            return None
        with self._lock:
            n = counts.get(ckey, 0)
            counts[ckey] = n + 1
            if fault.times is not None and self._fired.get(point, 0) >= fault.times:
                return None
            hit, draw = self._hit(fault, word, n)
            if not hit:
                return None
            self._fired[point] = self._fired.get(point, 0) + 1
        return Decision(fault=fault, draw=draw)

    def decide(self, point: str) -> Decision | None:
        """The hot-path decision for one call at ``point``: ``None`` (the
        overwhelmingly common answer) or the fired :class:`Decision`.
        Unarmed points don't count calls — their schedule is independent
        of which other seams happen to be compiled in."""
        return self._decide(
            point, zlib.crc32(point.encode()), self._calls, point
        )

    def decide_pair(self, point: str, pair: str) -> Decision | None:
        """Like :meth:`decide`, but the schedule is keyed by a ``pair``
        label as well (``"router->w1"``): the first counter word mixes
        ``crc32(point) ^ crc32(pair)``, the second counts calls *for that
        pair*, so every network link sees its own pure-function schedule
        under one armed point — a seeded connectivity MASK, not a global
        coin.  ``times`` still bounds total fires across all pairs (a
        partition drill must heal)."""
        word = zlib.crc32(point.encode()) ^ zlib.crc32(pair.encode())
        return self._decide(point, word, self._pair_calls, (point, pair))

    def _preview(
        self, point: str, word: int, calls: int, bound: bool
    ) -> list[bool]:
        fault = self.faults.get(point)
        if fault is None:
            return [False] * calls
        out: list[bool] = []
        fired = 0
        for n in range(calls):
            if bound and fault.times is not None and fired >= fault.times:
                out.append(False)
                continue
            hit, _ = self._hit(fault, word, n)
            out.append(hit)
            fired += hit
        return out

    def preview_pair(self, point: str, pair: str, calls: int) -> list[bool]:
        """The pure fire/no-fire schedule :meth:`decide_pair` would draw
        for one pair's first ``calls`` calls, without the live counters
        (and without the cross-pair ``times`` interaction — this is the
        per-link mask the determinism tests compare)."""
        word = zlib.crc32(point.encode()) ^ zlib.crc32(pair.encode())
        return self._preview(point, word, calls, bound=False)

    def preview(self, point: str, calls: int) -> list[bool]:
        """The pure fire/no-fire schedule for the first ``calls`` calls at
        ``point``, WITHOUT touching the live counters — what the
        determinism tests compare across plans of equal seed."""
        return self._preview(
            point, zlib.crc32(point.encode()), calls, bound=True
        )


# -- the process-global arming seam ------------------------------------------
_PLAN: ChaosPlan | None = None
_INJECTIONS = 0
_COUNTS: dict[tuple[str, str], int] = {}
_REG_FAMILY = None  # optional obs counter family (chaos_injections_total)
_STATE_LOCK = threading.Lock()


def arm(plan: ChaosPlan | dict | str) -> ChaosPlan:
    """Install ``plan`` (a :class:`ChaosPlan` or a spec) process-wide."""
    global _PLAN
    if not isinstance(plan, ChaosPlan):
        plan = ChaosPlan.from_spec(plan)
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def armed() -> bool:
    return _PLAN is not None


def active_plan() -> ChaosPlan | None:
    return _PLAN


@contextlib.contextmanager
def armed_plan(plan: ChaosPlan | dict | str):
    """Scope a plan to a ``with`` block (tests): always disarms on exit."""
    p = arm(plan)
    try:
        yield p
    finally:
        disarm()


def maybe_arm_from_env(env=os.environ) -> ChaosPlan | None:
    """Arm from ``TPU_LIFE_CHAOS`` when set (CLI entry; worker
    subprocesses inherit the exported spec).  A malformed spec raises the
    typed :class:`ChaosError` — a drill must never run un-armed because
    its plan had a typo."""
    raw = env.get(ENV_VAR)
    if not raw:
        return None
    return arm(raw)


def injection_count() -> int:
    """Total injections fired in this process — the zero-overhead-
    disarmed probe (mirrors ``autotune.trial_count`` / ``obs.span_count``):
    the conftest guard asserts it stays 0 across every test that never
    armed a plan, i.e. across the whole tier-1 suite outside the chaos
    tests themselves."""
    return _INJECTIONS


def counts() -> dict[str, dict[str, int]]:
    """Fired injections by point and outcome, for drill summaries."""
    with _STATE_LOCK:
        out: dict[str, dict[str, int]] = {}
        for (point, outcome), n in _COUNTS.items():
            out.setdefault(point, {})[outcome] = n
        return out


def bind_registry(registry) -> None:
    """Register ``chaos_injections_total{point,outcome}`` on an obs
    registry; later fires tick it (the serve/fleet tiers bind their own
    registries so injections surface in /metrics and the JSONL snapshot).
    Binding is unconditional and cheap — the family simply stays at zero
    (and invisible: no primed series) in a disarmed process."""
    global _REG_FAMILY
    _REG_FAMILY = registry.counter(
        "chaos_injections_total",
        "chaos faults injected, by point and outcome",
        labels=("point", "outcome"),
    )


def _record(point: str, outcome: str) -> None:
    global _INJECTIONS
    with _STATE_LOCK:
        _INJECTIONS += 1
        _COUNTS[(point, outcome)] = _COUNTS.get((point, outcome), 0) + 1
    fam = _REG_FAMILY
    if fam is not None:
        fam.labels(point=point, outcome=outcome).inc()
    # the trace marker (docs/OBSERVABILITY.md "Distributed tracing"):
    # every fired injection is an instant event in whatever timeline is
    # active, so a drill's merged trace shows fault <-> latency
    # correlation instead of only counters.  instant() is the standard
    # one-global-check no-op when no tracer is active; a fire only
    # happens under an armed plan, so the disarmed path never gets here.
    _trace.instant("chaos.injection", point=point, decision=outcome)
    # and the flight-recorder twin: injections are postmortem decisions
    _flight.record("injection", point=point, decision=outcome)


# -- the seam helpers (all no-ops when disarmed) -----------------------------
def decide(point: str) -> Decision | None:
    """The generic seam check: the fired :class:`Decision` or ``None``.
    Seams with bespoke behavior (corruption, resets) use this and act on
    the decision themselves, recording via :func:`record_fire`."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.decide(point)


def record_fire(point: str, outcome: str) -> None:
    """Count a fire a seam executed itself (paired with :func:`decide`)."""
    _record(point, outcome)


def inject(point: str) -> None:
    """Raise the configured exception when ``point`` fires; no-op
    otherwise.  The exception TYPE is what the seam's real handlers
    catch — OSError for the spill paths, ``recovery.InjectedFault``
    (RECOVERABLE) for the engine chunk seams — so an injection exercises
    the production handling, not a parallel code path."""
    plan = _PLAN
    if plan is None:
        return
    d = plan.decide(point)
    if d is None:
        return
    _record(point, d.fault.mode)
    mode = d.fault.mode
    if mode == "enospc":
        raise OSError(
            errno.ENOSPC, f"chaos: injected ENOSPC at {point} (seed {plan.seed})"
        )
    if mode == "oserror":
        raise OSError(f"chaos: injected I/O failure at {point} (seed {plan.seed})")
    if mode == "fault":
        from tpu_life.runtime import recovery

        raise recovery.InjectedFault(
            f"chaos: injected device fault at {point} (seed {plan.seed})"
        )
    if mode == "oom":
        from tpu_life.runtime import recovery

        # the message carries the real XLA OOM marker so the production
        # classifier (recovery.is_oom) — and therefore the OOM-specific
        # recovery ladder — is what an injection exercises
        raise recovery.InjectedFault(
            f"RESOURCE_EXHAUSTED: chaos: injected device OOM at {point} "
            f"(seed {plan.seed})"
        )
    raise ChaosError(f"point {point} cannot inject mode {mode}")  # pragma: no cover


def delay(point: str) -> float:
    """Seconds to sleep at a timing seam (0.0 when disarmed / unfired)."""
    plan = _PLAN
    if plan is None:
        return 0.0
    d = plan.decide(point)
    if d is None:
        return 0.0
    _record(point, d.fault.mode)
    return d.fault.seconds


def skew(point: str) -> float:
    """A deterministic clock skew in ``[0, seconds]`` — the draw word
    picks the magnitude, so the skew schedule replays from the seed."""
    plan = _PLAN
    if plan is None:
        return 0.0
    d = plan.decide(point)
    if d is None:
        return 0.0
    _record(point, d.fault.mode)
    return d.fault.seconds * (d.draw / 4294967296.0)


def corrupt(point: str, data: bytes) -> bytes:
    """Mangle published bytes when ``point`` fires: ``bitflip`` flips one
    deterministically chosen bit, ``truncate`` drops the tail — the two
    disk-rot shapes ``snapshot_intact`` exists to catch."""
    plan = _PLAN
    if plan is None or not data:
        return data
    d = plan.decide(point)
    if d is None:
        return data
    _record(point, d.fault.mode)
    if d.fault.mode == "truncate":
        return data[: max(1, len(data) // 2)]
    buf = bytearray(data)
    bit = d.draw % (len(buf) * 8)
    buf[bit // 8] ^= 1 << (bit % 8)
    return bytes(buf)


def partitioned(src: str, dst: str) -> bool:
    """True when the seeded connectivity mask severs the ``src -> dst``
    link for this call (the ``net.partition`` point, drawn per pair via
    :meth:`ChaosPlan.decide_pair`).  Callers translate True into their
    transport's honest unreachable shape — a connect that never
    establishes — so the production partition handling is what runs."""
    plan = _PLAN
    if plan is None:
        return False
    d = plan.decide_pair("net.partition", f"{src}->{dst}")
    if d is None:
        return False
    _record("net.partition", d.fault.mode)
    return True


def crash(point: str) -> None:
    """``os._exit`` the process when ``point`` fires (the worker-crash
    seam: a SIGKILL-grade death — no atexit, no drain, no flush)."""
    plan = _PLAN
    if plan is None:
        return
    d = plan.decide(point)
    if d is None:
        return
    _record(point, d.fault.mode)
    from tpu_life.runtime.metrics import log

    log.error("chaos: injected crash at %s (seed %d)", point, plan.seed)
    os._exit(23)


__all__ = [
    "ENV_VAR",
    "POINTS",
    "ChaosError",
    "ChaosPlan",
    "Decision",
    "Fault",
    "active_plan",
    "arm",
    "armed",
    "armed_plan",
    "bind_registry",
    "corrupt",
    "counts",
    "crash",
    "decide",
    "delay",
    "disarm",
    "inject",
    "injection_count",
    "maybe_arm_from_env",
    "partitioned",
    "record_fire",
    "skew",
]
