"""The chaos drill: a seeded, machine-verified fleet-survival exercise.

``run_drill`` drives a REAL N-worker CPU fleet (subprocess gateways
behind the router, spill-backed failover on) under a seeded fault
schedule — the armed :mod:`tpu_life.chaos` plan plus drill-driven
SIGKILLs — while a mixed det+ising workload with staggered budgets flows
through the standard client protocol.  Nothing in the serving stack is
modified for the drill; the faults land at the production seams.

The drill then checks the **invariants** (docs/CHAOS.md) that define
"robust" for this fleet:

- ``all_terminal``: every accepted session reaches a terminal
  observation (done / typed 410 / failed) within the wait budget — no
  sid polls "migrating" or "running" forever (the stuck-MIGRATING
  watchdog's contract).
- ``bit_identity``: every session observed DONE returns a board
  byte-identical to its solo oracle (``run_np`` / ``MCHostRunner``) —
  failover, resets and retries may delay an answer, never change it.
- ``legal_410``: every terminal loss is TYPED — a ``worker_lost`` 410
  carries a reason from the legal set, a failed session carries its
  error string.  Silent loss (a 404 for an accepted sid, an unreasoned
  410) is a violation.
- ``no_lost_work``: every workload item ultimately yields its oracle
  board.  Typed losses are recoverable by the documented client
  recourse — resubmit from scratch — and the drill plays that client,
  so "no lost accepted work" means: loss is bounded, typed, and always
  recoverable, never silent or sticky.
- ``recovery_bounded``: after each SIGKILL the supervisor returns the
  fleet to full ready strength within ``recovery_bound_s``.
- ``metrics_consistent``: the fleet's merged accounting adds up —
  ``fleet_routed_total`` equals the sessions the clients actually got
  accepted (201s), and the migration counters cover every post-kill
  outcome.

Every summary is stamped with the chaos **seed** and the plan
**digest**: a failed CI drill prints its seed, and rerunning with that
seed replays the exact injection schedule (docs/CHAOS.md "seed replay").
"""

from __future__ import annotations

import json
import os
import signal
import time
import urllib.error
import urllib.request
import zlib
from dataclasses import dataclass, field

import numpy as np

from tpu_life import chaos, mc
from tpu_life.gateway import protocol
from tpu_life.gateway.client import GatewayClient
from tpu_life.mc.engine import MCHostRunner
from tpu_life.mc.prng import key_halves, threefry2x32
from tpu_life.models.rules import get_rule
from tpu_life.ops.reference import run_np
from tpu_life.runtime.metrics import log

#: 410 reasons the durability contract is allowed to answer (docs/FLEET.md).
LEGAL_410_REASONS = frozenset(
    {"never_snapshotted", "spill_corrupt", "migration_failed", "spill_disabled"}
)

#: The default fault mix for ``tpu-life chaos`` / ``bench --chaos``: every
#: armed family fires a BOUNDED number of times (``times``), so the drill
#: exercises each seam without degenerating into pure noise — and the
#: bounds make "did every armed point actually fire?" a deterministic
#: question on any run long enough to reach each seam.
DEFAULT_POINTS: dict[str, dict] = {
    "spill.write": {"rate": 1.0, "mode": "enospc", "times": 1},
    "snapshot.corrupt": {"rate": 1.0, "mode": "bitflip", "times": 2},
    "router.submit.reset": {"rate": 1.0, "mode": "reset", "times": 2},
    "router.poll.reset": {"rate": 0.02, "mode": "mid_body"},
    # low-rate so the fault lands MID-flight (a first-dispatch wipeout
    # would just retest admission); bounded so one fault, not a storm
    "engine.dispatch": {"rate": 0.02, "mode": "fault", "times": 1},
}

#: The governor drill's fault mix (``tpu-life chaos --governor``,
#: docs/SERVING.md "Resource governance"): device OOMs that the
#: in-place recovery ladder must MASK (the worker survives, sessions
#: finish byte-identical on halved-chunk/host-demotion), and a wedged
#: settle that only the watchdog -> readyz-500 -> supervisor-recycle ->
#: migration path can rescue.  Low rates land the faults mid-flight;
#: ``seconds`` is far past any sane settle deadline so the wedge can
#: never clear itself by luck.
GOVERNOR_POINTS: dict[str, dict] = {
    "engine.oom": {"rate": 0.04, "mode": "oom", "times": 2},
    "engine.wedge": {"rate": 0.015, "mode": "sleep", "times": 1,
                     "seconds": 30.0},
}

#: The stream drill's fault mix (``tpu-life chaos --stream``,
#: docs/STREAMING.md "Chaos"): torn worker streams mid-frame (the
#: fan-out puller must reconnect at its cursor and the watcher must see
#: GAPLESS seqs) and a stalled router->watcher write (absorbed by the
#: broadcast buffer, never propagated to the pump).  ``seconds`` stays
#: well under the buffer's slack so the stall is exercised without
#: shedding the drill's own watchers.
STREAM_POINTS: dict[str, dict] = {
    "stream.reset": {"rate": 0.1, "mode": "reset", "times": 2},
    "watch.slow_reader": {"rate": 0.05, "mode": "sleep", "times": 2,
                          "seconds": 0.4},
}

#: The surge drill's fault mix (``tpu-life chaos --surge``,
#: docs/FLEET.md "Autoscaling"): one recruit refused at the worst moment
#: (the control loop must hold WITHOUT arming its cooldown and land the
#: recruit on the next tick) and one release steered onto the BUSIEST
#: worker instead of the idlest (graceful drain must still lose no
#: session).  Both fire in the fleet process — the autoscaler's seams.
SURGE_POINTS: dict[str, dict] = {
    "scale.recruit.fail": {"rate": 1.0, "mode": "refuse", "times": 1},
    "scale.release.race": {"rate": 1.0, "mode": "race", "times": 1},
}


@dataclass
class DrillConfig:
    seed: int = 0
    workers: int = 2
    det_sessions: int = 6
    ising_sessions: int = 2
    size: int = 20  # det board edge (ising boards are 16x16 — even dims)
    steps: int = 900  # base budget; staggered downward per session
    kills: int = 1
    min_progress: int = 8  # steps a victim must have banked before a kill
    points: dict | None = None  # chaos plan points (None = DEFAULT_POINTS)
    backend: str = "numpy"  # worker engine executor (CPU drills)
    capacity: int = 4
    chunk_steps: int = 2
    spill_every: int = 1
    resubmit_lost: int = 3  # client recourse: resubmits per lost item
    recovery_bound_s: float = 60.0
    wait_timeout_s: float = 180.0
    migrate_stuck_after_s: float = 60.0
    workdir: str = "."  # spill/ and logs/ land under here
    summary_file: str | None = None  # append the summary as one JSONL line
    # the governor drill (docs/SERVING.md "Resource governance"): arm
    # GOVERNOR_POINTS by default, run every worker with the wedge
    # watchdog at ``settle_deadline_s``, track supervisor recycles, and
    # verify the extra ``governor`` invariant (OOM masked — no worker
    # death outside the wedge-recycle/kill schedule; both points fired)
    governor: bool = False
    settle_deadline_s: float = 1.0
    # the stream drill (docs/STREAMING.md): arm STREAM_POINTS by
    # default, schedule mid-run edits on every session, hang live
    # watchers on each sid through the SIGKILL, and verify the extra
    # ``stream_continuity`` invariant — gapless seqs across failover,
    # watcher agreement, reconstruction == the replay_edit_log oracle
    stream: bool = False
    lenia_sessions: int = 1  # stream drill only: continuous-tier sids
    watchers_per_session: int = 2
    # the surge drill (docs/FLEET.md "Autoscaling" + docs/SERVING.md
    # "Tenant QoS"): a fleet with a standby pool and a live autoscaler
    # rides a ``surge_factor``x admission burst split between a
    # guaranteed and a best-effort tenant, and the drill verifies the
    # extra ``scale`` invariant (recruited to full strength through the
    # burst, released back to the base after it, both scale chaos points
    # fired) and ``qos`` invariant (every refusal typed and best-effort-
    # only, guaranteed-tenant admission p99 bounded)
    surge: bool = False
    standby: int = 2  # parked slots the autoscaler may recruit
    surge_factor: int = 10  # burst size = surge_factor x det_sessions
    qos_p99_bound_s: float = 5.0  # guaranteed-tenant submit p99 bound
    scale_wait_s: float = 90.0  # budget for the post-burst release-back


@dataclass
class WorkItem:
    """One workload trajectory and its precomputed solo oracle."""

    tag: str
    rule: str
    board: np.ndarray
    steps: int
    seed: int
    temperature: float | None
    oracle: bytes
    sid: str | None = None
    outcome: str = "pending"  # done | lost | failed | pending
    detail: str = ""
    resubmits: int = 0
    delivered: bool = False  # a DONE answer matched the oracle
    # stream drill fields: the pre-scheduled steering this session
    # carries ([[step, cells], ...]) — its oracle is then the
    # ``replay_edit_log`` of the same log — and whether the oracle
    # compare is allclose (continuous tier) rather than byte-equal
    edits: list = field(default_factory=list)
    continuous: bool = False
    # surge drill fields: which tenant this item submits as (the API key
    # carried on its requests) and which traffic phase it belongs to
    api_key: str | None = None
    phase: str = ""  # "trickle" | "burst"


def _build_stream_items(cfg: DrillConfig) -> list[WorkItem]:
    """The stream drill's workload: every session carries pre-scheduled
    mid-run edits, and its oracle is ``replay_edit_log`` of the same log
    run solo (at a DIFFERENT chunk cadence than the fleet's, so the
    compare also proves edit placement is chunk-independent)."""
    from tpu_life.models.lenia import seeded_board as lenia_board
    from tpu_life.serve.stream import replay_edit_log

    items: list[WorkItem] = []

    def edits_for(steps: int, value) -> list:
        zero = 0.0 if isinstance(value, float) else 0
        return [
            [max(1, steps // 3), [[1, 1, value], [2, 3, value]]],
            [max(2, (2 * steps) // 3), [[3, 4, zero], [1, 1, value]]],
        ]

    def oracle(board, rule, steps, edits, *, seed=None, temperature=None):
        return replay_edit_log(
            board, rule, steps, edits,
            seed=seed, temperature=temperature,
            chunk_steps=max(3, cfg.chunk_steps + 1),
        )

    for i in range(cfg.det_sessions):
        steps = max(
            cfg.chunk_steps * cfg.min_progress,
            cfg.steps - (cfg.steps * i) // (2 * max(cfg.det_sessions, 1)),
        )
        seed = cfg.seed * 1000 + i
        board = mc.seeded_board(cfg.size, cfg.size, 0.45, seed=seed)
        edits = edits_for(steps, 1)
        items.append(
            WorkItem(
                tag=f"det{i}",
                rule="conway",
                board=board,
                steps=steps,
                seed=seed,
                temperature=None,
                oracle=oracle(board, "conway", steps, edits).tobytes(),
                edits=edits,
            )
        )
    for i in range(cfg.ising_sessions):
        seed = cfg.seed * 1000 + 500 + i
        temp = 2.0 + 0.3 * i
        steps = max(cfg.chunk_steps * cfg.min_progress, cfg.steps // 2)
        board = mc.seeded_board(16, 16, 0.5, seed=seed)
        edits = edits_for(steps, 1)
        items.append(
            WorkItem(
                tag=f"ising{i}",
                rule="ising",
                board=board,
                steps=steps,
                seed=seed,
                temperature=temp,
                oracle=oracle(
                    board, "ising", steps, edits, seed=seed, temperature=temp
                ).tobytes(),
                edits=edits,
            )
        )
    for i in range(cfg.lenia_sessions):
        seed = cfg.seed * 1000 + 800 + i
        steps = max(cfg.chunk_steps * cfg.min_progress, cfg.steps // 3)
        board = lenia_board(32, 32, 0.4, seed=seed)
        edits = edits_for(steps, 0.75)
        items.append(
            WorkItem(
                tag=f"lenia{i}",
                rule="lenia",
                board=board,
                steps=steps,
                seed=seed,
                temperature=None,
                oracle=oracle(board, "lenia", steps, edits).tobytes(),
                edits=edits,
                continuous=True,
            )
        )
    return items


#: The surge drill's tenant API keys (seeded fixtures, not secrets).
SURGE_GOLD_KEY = "drill-gold-key"
SURGE_FREE_KEY = "drill-free-key"


def _build_surge_items(cfg: DrillConfig) -> list[WorkItem]:
    """The surge workload: a 1x trickle of guaranteed-tenant sessions,
    then a ``surge_factor``x burst split between the guaranteed and the
    best-effort tenant.  All conway with precomputed oracles — the
    standard bit_identity / no_lost_work invariants apply unchanged."""
    rule = get_rule("conway")
    items: list[WorkItem] = []

    def det_item(tag: str, i: int, key: str, phase: str) -> WorkItem:
        steps = max(
            cfg.chunk_steps * cfg.min_progress,
            cfg.steps - (cfg.steps * (i % 7)) // 14,
        )
        seed = cfg.seed * 1000 + i
        board = mc.seeded_board(cfg.size, cfg.size, 0.45, seed=seed)
        return WorkItem(
            tag=tag,
            rule="conway",
            board=board,
            steps=steps,
            seed=seed,
            temperature=None,
            oracle=run_np(board, rule, steps).tobytes(),
            api_key=key,
            phase=phase,
        )

    for i in range(cfg.det_sessions):
        items.append(det_item(f"trickle{i}", i, SURGE_GOLD_KEY, "trickle"))
    burst = cfg.surge_factor * cfg.det_sessions
    for i in range(burst):
        key = SURGE_GOLD_KEY if i % 2 == 0 else SURGE_FREE_KEY
        tenant = "gold" if i % 2 == 0 else "free"
        items.append(
            det_item(f"burst-{tenant}{i}", 100 + i, key, "burst")
        )
    return items


def _build_items(cfg: DrillConfig) -> list[WorkItem]:
    if cfg.stream:
        return _build_stream_items(cfg)
    if cfg.surge:
        return _build_surge_items(cfg)
    items: list[WorkItem] = []
    rule = get_rule("conway")
    for i in range(cfg.det_sessions):
        # staggered budgets: the same uneven mix the serve benches drive
        steps = max(cfg.chunk_steps * cfg.min_progress,
                    cfg.steps - (cfg.steps * i) // (2 * max(cfg.det_sessions, 1)))
        seed = cfg.seed * 1000 + i
        board = mc.seeded_board(cfg.size, cfg.size, 0.45, seed=seed)
        items.append(
            WorkItem(
                tag=f"det{i}",
                rule="conway",
                board=board,
                steps=steps,
                seed=seed,
                temperature=None,
                oracle=run_np(board, rule, steps).tobytes(),
            )
        )
    irule = get_rule("ising")
    for i in range(cfg.ising_sessions):
        seed = cfg.seed * 1000 + 500 + i
        temp = 2.0 + 0.3 * i
        steps = max(cfg.chunk_steps * cfg.min_progress, cfg.steps // 2)
        board = mc.seeded_board(16, 16, 0.5, seed=seed)
        oracle = MCHostRunner(board, irule, seed=seed, temperature=temp)
        oracle.advance(steps)
        items.append(
            WorkItem(
                tag=f"ising{i}",
                rule="ising",
                board=board,
                steps=steps,
                seed=seed,
                temperature=temp,
                oracle=oracle.fetch().tobytes(),
            )
        )
    return items


def _http_json(url: str, timeout: float = 10.0) -> tuple[int, dict]:
    """GET returning (status, parsed body) — errors included, so the
    drill reads full typed error envelopes (reason fields and all)."""
    try:
        with urllib.request.urlopen(
            urllib.request.Request(url), timeout=timeout
        ) as resp:
            return resp.status, _parse(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, _parse(e.read())


def _parse(raw: bytes) -> dict:
    try:
        doc = json.loads(raw or b"{}")
        return doc if isinstance(doc, dict) else {}
    except json.JSONDecodeError:
        return {}


def _oracle_match(item: WorkItem, board: np.ndarray) -> bool:
    """DONE board vs precomputed oracle: byte-equal for the discrete
    tiers; allclose at ``models.lenia.FLOAT_ATOL`` for the continuous
    tier (the masked-threshold delta tolerance, docs/STREAMING.md)."""
    if not item.continuous:
        return board.tobytes() == item.oracle
    from tpu_life.models.lenia import FLOAT_ATOL

    want = np.frombuffer(item.oracle, dtype="<f4").reshape(board.shape)
    return bool(
        np.allclose(np.asarray(board, dtype=np.float32), want, atol=FLOAT_ATOL)
    )


class _Driller:
    """One drill run's state: the fleet, the client, the verdicts."""

    def __init__(self, cfg: DrillConfig):
        self.cfg = cfg
        self.items = _build_items(cfg)
        if cfg.points is not None:
            points = cfg.points
        elif cfg.governor:
            points = GOVERNOR_POINTS
        elif cfg.stream:
            points = STREAM_POINTS
        elif cfg.surge:
            points = SURGE_POINTS
        else:
            points = DEFAULT_POINTS
        self.plan = chaos.ChaosPlan(cfg.seed, points)
        self.accepted = 0  # 201s the clients received (== routed, invariant)
        self.kills: list[dict] = []
        self.recycles: list[dict] = []  # supervisor unready-recycles observed
        self.extra_invariants: list[str] = []
        self.violations: dict[str, list[str]] = {}
        self.injection_scrapes: dict[str, dict[str, float]] = {}
        self.fleet = None
        self.base_url = ""
        # surge drill evidence (populated by _surge_submit): typed
        # best-effort sheds observed, guaranteed-tenant admission
        # latencies, and any refusal the QoS contract forbids
        self.surge_sheds: list[dict] = []
        self.surge_gold_lat_s: list[float] = []
        # the same latencies keyed by phase: "trickle" is the 1x
        # baseline, "burst" the surge_factor-x spike — the pair the
        # BENCH_surge record reports as p99 at 1x vs 10x
        self.surge_gold_lat_phase: dict[str, list[float]] = {}
        self.surge_gold_refusals: list[str] = []
        self.surge_bad_refusals: list[str] = []

    # -- plumbing ----------------------------------------------------------
    def violate(self, invariant: str, detail: str) -> None:
        self.violations.setdefault(invariant, []).append(detail)
        log.error("chaos drill: %s violated: %s", invariant, detail)

    def _draw(self, label: str, n: int) -> int:
        """A seeded drill-side draw (victim choice) — same Threefry
        discipline as the plan, so the kill schedule replays too."""
        k0, k1 = key_halves(self.cfg.seed)
        u, _ = threefry2x32(
            np, k0, k1, np.uint32(zlib.crc32(label.encode())), np.uint32(n)
        )
        return int(u)

    def _scrape_injections(self) -> None:
        """Merge chaos_injections_total from the fleet's merged /metrics
        (fleet-process + live workers) into the running per-point view —
        best-effort evidence of which seams actually fired."""
        try:
            req = urllib.request.Request(self.base_url + "/metrics")
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                text = resp.read().decode()
        except Exception:
            return
        for line in text.splitlines():
            # chaos_injections_total: live per-process counters (the
            # fleet's own + each reachable worker's, worker-labeled).
            # fleet_chaos_injections: the supervisor's last-seen retention
            # per worker — it SURVIVES the worker's death, so the merged
            # accounting is exact rather than a pre-kill floor (the max
            # below dedups it against the live series it mirrors).
            if not (
                line.startswith("chaos_injections_total{")
                or line.startswith("fleet_chaos_injections{")
            ):
                continue
            labels, _, value = line.rpartition(" ")
            point = outcome = worker = ""
            inner = labels[labels.find("{") + 1 : labels.rfind("}")]
            for part in inner.split(","):
                k, _, v = part.partition("=")
                v = v.strip('"')
                if k == "point":
                    point = v
                elif k == "outcome":
                    outcome = v
                elif k == "worker":
                    worker = v
            if not point:
                continue
            series = self.injection_scrapes.setdefault(point, {})
            key = f"{worker or 'fleet'}:{outcome}"
            try:
                # counters reset when a worker respawns under the same
                # label: keep the max ever seen per series — a floor on
                # the true total, never an overcount of one incarnation
                series[key] = max(series.get(key, 0.0), float(value))
            except ValueError:
                continue

    def injections_by_point(self) -> dict[str, float]:
        return {
            point: sum(series.values())
            for point, series in sorted(self.injection_scrapes.items())
        }

    # -- workload ----------------------------------------------------------
    def submit_item(self, client: GatewayClient, item: WorkItem) -> bool:
        try:
            item.sid = client.submit(
                board=item.board,
                rule=item.rule,
                steps=item.steps,
                seed=item.seed,
                temperature=item.temperature,
                scheduled_edits=item.edits or None,
            )
        except Exception as e:  # noqa: BLE001 - a refused submit is data
            item.outcome = "rejected"
            item.detail = str(e)
            return False
        self.accepted += 1
        item.outcome = "pending"
        return True

    def poll_until_terminal(self, client: GatewayClient, item: WorkItem) -> None:
        """Poll one sid to a terminal observation, riding out transient
        502s (injected resets) and garbled bodies; on DONE fetch and
        byte-check the result; on typed loss record the reason."""
        deadline = time.monotonic() + self.cfg.wait_timeout_s
        url = f"{self.base_url}/v1/sessions/{item.sid}"
        while True:
            if time.monotonic() > deadline:
                item.outcome = "stuck"
                item.detail = "never reached a terminal observation"
                self.violate(
                    "all_terminal",
                    f"{item.tag} ({item.sid}) still non-terminal after "
                    f"{self.cfg.wait_timeout_s:.0f}s",
                )
                return
            try:
                status, doc = _http_json(url)
            except Exception as e:  # noqa: BLE001 - transport noise: retry
                log.debug("chaos drill: poll %s transport error %s", item.sid, e)
                time.sleep(0.1)
                continue
            if status == 200 and "finished" not in doc:
                # a chaos mid-body truncation: retry, it is transient
                time.sleep(0.05)
                continue
            if status == 200 and not doc["finished"]:
                time.sleep(0.05)
                continue
            if status == 200:
                state = doc.get("state")
                if state == "done":
                    self._check_result(item)
                else:
                    item.outcome = "failed"
                    item.detail = str(doc.get("error") or "")
                    if not item.detail:
                        self.violate(
                            "legal_410",
                            f"{item.tag} failed without an error string",
                        )
                return
            if status in (409, 502):
                # migrating / injected upstream ambiguity: both transient
                time.sleep(0.1)
                continue
            if status == 410:
                err = doc.get("error") or {}
                item.outcome = "lost"
                item.detail = str(err.get("reason") or err.get("code") or "")
                if err.get("code") == "worker_lost":
                    if err.get("reason") not in LEGAL_410_REASONS:
                        self.violate(
                            "legal_410",
                            f"{item.tag} 410 with illegal reason "
                            f"{err.get('reason')!r}",
                        )
                elif err.get("code") != "session_failed":
                    self.violate(
                        "legal_410",
                        f"{item.tag} 410 with unexpected code {err.get('code')!r}",
                    )
                return
            # anything else for an accepted sid is silent loss (404 means
            # the fleet forgot a session it admitted)
            item.outcome = "lost"
            item.detail = f"unexpected status {status}"
            self.violate(
                "legal_410", f"{item.tag} answered {status} {doc!r}"
            )
            return

    def _check_result(self, item: WorkItem) -> None:
        url = f"{self.base_url}/v1/sessions/{item.sid}/result?format=raw"
        deadline = time.monotonic() + 30.0
        while True:
            try:
                status, doc = _http_json(url)
            except Exception:  # noqa: BLE001 - transport noise: retry
                status, doc = 502, {}
            if status == 200:
                try:
                    board = protocol.decode_result(doc)
                except Exception:  # noqa: BLE001 - injected mid-body garble
                    board = None
                if board is not None:
                    item.outcome = "done"
                    if _oracle_match(item, board):
                        item.delivered = True
                    else:
                        self.violate(
                            "bit_identity",
                            f"{item.tag} ({item.sid}) differs from its "
                            f"solo oracle",
                        )
                    return
            elif status not in (409, 502):
                # DONE then no board is a contract violation, not retry noise
                self.violate(
                    "bit_identity",
                    f"{item.tag} done but result answered {status}",
                )
                item.outcome = "failed"
                item.detail = f"result {status}"
                return
            if time.monotonic() > deadline:
                self.violate(
                    "bit_identity",
                    f"{item.tag} done but its result never materialized",
                )
                item.outcome = "failed"
                item.detail = "result unavailable"
                return
            time.sleep(0.1)

    # -- the kill schedule --------------------------------------------------
    def run_kills(self, client: GatewayClient) -> None:
        sup = self.fleet.supervisor
        for k in range(self.cfg.kills):
            victim = self._wait_for_victim(client, k)
            if victim == "drained":
                # every session finished before this kill could land: not
                # an invariant violation, but the summary shows the gap
                self.kills.append({"worker": None, "skipped": "drained"})
                continue
            if victim is None:
                self.violate(
                    "recovery_bounded",
                    f"kill {k}: no worker ever owned a progressed session",
                )
                return
            self._scrape_injections()  # evidence BEFORE the worker dies
            gen0 = victim.generation
            t0 = time.monotonic()
            os.kill(victim.proc.pid, signal.SIGKILL)
            log.info("chaos drill: SIGKILL %s (kill %d)", victim.name, k)
            # recovery = kill -> the VICTIM's successor generation answers
            # ready again AND the fleet is back to full ready strength.
            # Requiring the generation bump keeps the timer honest: right
            # after the SIGKILL the supervisor may not have observed the
            # death yet, and "everything still looks ready" must not
            # count as an instant recovery.
            deadline = t0 + self.cfg.recovery_bound_s
            while not (
                victim.generation > gen0
                and len(sup.ready_workers()) >= self.cfg.workers
            ):
                if time.monotonic() > deadline:
                    self.kills.append(
                        {"worker": victim.name, "recovery_s": None}
                    )
                    self.violate(
                        "recovery_bounded",
                        f"kill {k} ({victim.name}): fleet not back to "
                        f"{self.cfg.workers} ready within "
                        f"{self.cfg.recovery_bound_s:.0f}s",
                    )
                    return
                time.sleep(0.05)
            self.kills.append(
                {"worker": victim.name, "recovery_s": time.monotonic() - t0}
            )

    def _wait_for_victim(self, client: GatewayClient, k: int):
        """A ready worker owning at least one live, progressed session —
        chosen by a seeded draw among the candidates, so the kill
        schedule replays with the seed."""
        deadline = time.monotonic() + self.cfg.wait_timeout_s
        while time.monotonic() < deadline:
            owners: dict[str, int] = {}
            in_flight = 0
            for item in self.items:
                if item.sid is None or item.outcome != "pending":
                    continue
                try:
                    status, doc = _http_json(
                        f"{self.base_url}/v1/sessions/{item.sid}", timeout=5.0
                    )
                except Exception:  # noqa: BLE001
                    continue
                if status != 200 or doc.get("finished") is not False:
                    continue
                in_flight += 1
                worker = doc.get("worker")
                done = doc.get("steps_done", 0)
                if worker and done >= self.cfg.min_progress:
                    owners[worker] = owners.get(worker, 0) + 1
            if in_flight == 0:
                # every accepted session already finished: budgets were
                # too short for this kill — nothing left worth killing
                return "drained"
            ready = {w.name: w for w in self.fleet.supervisor.ready_workers()}
            candidates = sorted(n for n in owners if n in ready)
            if candidates:
                pick = self._draw("drill.kill", k) % len(candidates)
                return ready[candidates[pick]]
            time.sleep(0.1)
        return None

    # -- invariants ----------------------------------------------------------
    def check_metrics(self) -> None:
        stats = self.fleet.stats()
        routed = sum(stats.get("routed", {}).values())
        if routed != self.accepted:
            self.violate(
                "metrics_consistent",
                f"fleet_routed_total {routed} != accepted 201s {self.accepted}",
            )
        outcomes = {i.outcome for i in self.items}
        if "pending" in outcomes:
            self.violate(
                "metrics_consistent", "an item finished the drill still pending"
            )
        mig = stats.get("migrations", {})
        lost_410 = sum(1 for i in self.items if i.outcome == "lost")
        covered = sum(mig.values()) if mig else 0
        if lost_410 and not mig:
            self.violate(
                "metrics_consistent",
                f"{lost_410} typed losses but no migration accounting at all",
            )
        self._migration_summary = {"migrations": mig, "covered": covered}

    def verdicts(self) -> dict[str, dict]:
        out = {}
        names = (
            "all_terminal",
            "bit_identity",
            "legal_410",
            "no_lost_work",
            "recovery_bounded",
            "metrics_consistent",
            *self.extra_invariants,
        )
        for name in names:
            probs = self.violations.get(name, [])
            out[name] = {"ok": not probs, "violations": probs}
        return out


def _check_governor(d: "_Driller", fleet) -> None:
    """The governor invariant (docs/SERVING.md "Resource governance"),
    appended to the standard six when ``--governor`` is armed:

    - both governor points actually fired (a drill that never reached
      its seams must not certify anything);
    - every worker restart is accounted for by a wedge-recycle or a
      drill-driven SIGKILL — i.e. an OOM (or any other masked fault)
      never killed a worker.  Sessions' byte-identity and delivery are
      already covered by bit_identity / no_lost_work.
    """
    d.extra_invariants.append("governor")
    inj = d.injections_by_point()
    ooms = inj.get("engine.oom", 0)
    wedges = inj.get("engine.wedge", 0)
    if ooms < 1:
        d.violate(
            "governor",
            f"engine.oom never fired (injections: {inj}) — the OOM "
            f"masking path was not exercised; pick a seed that reaches it",
        )
    if wedges < 1:
        d.violate(
            "governor",
            f"engine.wedge never fired (injections: {inj}) — the wedge "
            f"watchdog path was not exercised; pick a seed that reaches it",
        )
    restarts = fleet.supervisor.restarts()
    sigkills = sum(1 for k in d.kills if k.get("worker"))
    allowed = wedges + sigkills
    if restarts > allowed:
        d.violate(
            "governor",
            f"{restarts:g} worker restart(s) but only {allowed:g} are "
            f"accounted for ({wedges:g} wedge fire(s) + {sigkills} "
            f"SIGKILL(s)) — a fault the governor must MASK killed a worker",
        )


class _StreamWatcher:
    """One live watcher of one fleet sid: a thread consuming the
    router's ndjson delta stream through the standard client,
    reconnecting at its cursor on tears (the documented watcher
    recourse) and folding every frame through ``apply_frame`` — so the
    drill can assert gapless seqs across the SIGKILL and compare the
    reconstruction against the ``replay_edit_log`` oracle."""

    def __init__(self, base_url: str, item: WorkItem, tag: str):
        import threading

        self.base_url = base_url
        self.item = item
        self.fsid = item.sid
        self.tag = tag
        self.frames: list[dict] = []
        self.board = None  # the running apply_frame reconstruction
        self.recon_error = ""  # first StreamProtocolError, if any
        self.error = ""
        self._t = threading.Thread(
            target=self._run, name=f"drill-watch-{tag}", daemon=True
        )

    def start(self) -> None:
        self._t.start()

    def join(self, timeout: float) -> None:
        self._t.join(timeout)

    @property
    def alive(self) -> bool:
        return self._t.is_alive()

    def _run(self) -> None:
        from tpu_life.serve.stream import StreamProtocolError, apply_frame

        client = GatewayClient(self.base_url, retries=4)
        cursor = 0
        attempts = 0
        while attempts <= 20:
            try:
                for frame in client.stream(self.fsid, cursor=cursor):
                    self.frames.append(frame)
                    seq = frame.get("seq")
                    if isinstance(seq, int):
                        cursor = seq + 1
                    try:
                        self.board = apply_frame(self.board, frame)
                    except StreamProtocolError as e:
                        if not self.recon_error:
                            self.recon_error = str(e)
                    if frame.get("type") in ("end", "shed"):
                        return
                # closed without a terminal frame: reconnect at cursor
                attempts += 1
            except Exception as e:  # noqa: BLE001 - transport tear: retry
                attempts += 1
                self.error = str(e)
                time.sleep(0.2)
        if not self.error:
            self.error = "reconnect budget exhausted without an end frame"


def _check_stream(d: "_Driller", watchers: list[_StreamWatcher]) -> None:
    """The stream invariant (docs/STREAMING.md), appended to the
    standard six when ``--stream`` is armed:

    - both stream points actually fired (torn upstream + stalled
      watcher write — the seams this drill exists to exercise);
    - every watcher terminated on a typed ``end`` with state ``done``
      (no hang, no shed, no synthetic ``lost``) and its sequence
      numbers are strictly consecutive ACROSS the mid-stream SIGKILL;
    - watchers of the same sid agree byte-for-byte on every shared seq
      (the fan-out broadcast is one stream, not N reconciliations);
    - each watcher's folded reconstruction equals the session's
      ``replay_edit_log`` oracle — byte-equal for the discrete tiers,
      allclose at ``FLOAT_ATOL`` for lenia — so bit-reproducibility
      under steering is machine-verified end to end.
    """
    d.extra_invariants.append("stream_continuity")
    inj = d.injections_by_point()
    local = {p: sum(c.values()) for p, c in chaos.counts().items()}
    for point in ("stream.reset", "watch.slow_reader"):
        if inj.get(point, 0) + local.get(point, 0) < 1:
            d.violate(
                "stream_continuity",
                f"{point} never fired (injections: {inj}) — the seam was "
                f"not exercised; pick a seed that reaches it",
            )
    for w in watchers:
        if w.alive:
            d.violate("stream_continuity", f"{w.tag} never terminated")
            continue
        if w.error:
            d.violate("stream_continuity", f"{w.tag}: {w.error}")
        seqs = [
            f["seq"] for f in w.frames if isinstance(f.get("seq"), int)
        ]
        for a, b in zip(seqs, seqs[1:]):
            if b != a + 1:
                d.violate(
                    "stream_continuity",
                    f"{w.tag} seq gap: {a} -> {b} (gapless-across-failover "
                    f"broken)",
                )
                break
        if w.item.resubmits:
            # the session was typed-lost and resubmitted under a fresh
            # sid: this watcher's ORIGINAL stream legitimately ended
            # early, so terminal-state/reconstruction checks don't apply
            continue
        last = w.frames[-1] if w.frames else {}
        if last.get("type") != "end" or last.get("state") != "done":
            d.violate(
                "stream_continuity",
                f"{w.tag} ended {last.get('type')!r}/{last.get('state')!r}, "
                f"expected end/done",
            )
            continue
        if w.recon_error:
            d.violate(
                "stream_continuity", f"{w.tag} reconstruction: {w.recon_error}"
            )
        elif w.board is None or not _oracle_match(w.item, w.board):
            d.violate(
                "stream_continuity",
                f"{w.tag} reconstruction differs from the replay_edit_log "
                f"oracle",
            )
    by_sid: dict[str, list[_StreamWatcher]] = {}
    for w in watchers:
        by_sid.setdefault(w.fsid, []).append(w)
    for fsid, ws in by_sid.items():
        maps = [
            {f["seq"]: f for f in w.frames if isinstance(f.get("seq"), int)}
            for w in ws
        ]
        shared = set(maps[0])
        for m in maps[1:]:
            shared &= set(m)
        for s in sorted(shared):
            if any(m[s] != maps[0][s] for m in maps[1:]):
                d.violate(
                    "stream_continuity",
                    f"watchers of {fsid} disagree at seq {s} — the "
                    f"broadcast is not byte-identical",
                )
                break


def _write_surge_policy(workdir: str) -> str:
    """The surge drill's tenant fixture (docs/SERVING.md "Tenant QoS"):
    a guaranteed ``gold`` tenant at 4x the weight of a best-effort
    ``free`` tenant, with the soft shed rung pulled LOW so the burst
    exercises best-effort shedding long before any hard limit — the
    ladder the qos invariant verifies (free sheds typed, gold never
    feels the wave)."""
    policy = {
        "tenants": [
            {
                "name": "gold",
                "tier": "guaranteed",
                "weight": 4,
                "api_keys": [SURGE_GOLD_KEY],
            },
            {
                "name": "free",
                "tier": "best_effort",
                "weight": 1,
                "api_keys": [SURGE_FREE_KEY],
            },
        ],
        "best_effort_water": 0.03,
    }
    path = os.path.join(workdir, "qos.json")
    with open(path, "w") as f:
        json.dump(policy, f)
    return path


def _surge_submit(d: "_Driller") -> None:
    """Drive the surge workload AS its tenants: raw (retries=0) clients
    so every refusal surfaces typed instead of being absorbed by client
    backoff.  Gold submits are single-attempt with admission latency
    recorded (the p99 the qos invariant bounds); free submits ride the
    documented shed recourse — honor Retry-After, resubmit — until
    admitted or the wait budget runs out."""
    from tpu_life.gateway.client import GatewayError

    cfg = d.cfg
    raw = {
        key: GatewayClient(d.base_url, api_key=key, retries=0)
        for key in (SURGE_GOLD_KEY, SURGE_FREE_KEY)
    }

    def attempt(item: WorkItem) -> str:
        gold = item.api_key == SURGE_GOLD_KEY
        t0 = time.monotonic()
        try:
            item.sid = raw[item.api_key].submit(
                board=item.board,
                rule=item.rule,
                steps=item.steps,
                seed=item.seed,
                temperature=item.temperature,
            )
        except GatewayError as e:
            if not gold and e.status == 503 and e.code == "shed_best_effort":
                d.surge_sheds.append(
                    {
                        "tag": item.tag,
                        "code": e.code,
                        "retry_after": e.retry_after,
                    }
                )
                return "shed"
            refusal = f"{item.tag}: {e.status} {e.code}"
            (d.surge_gold_refusals if gold else d.surge_bad_refusals).append(
                refusal
            )
            item.outcome = "rejected"
            item.detail = refusal
            return "refused"
        except Exception as e:  # noqa: BLE001 - raw client: no retries,
            # so transport noise at submit is indistinguishable from an
            # untyped refusal — record it as one (the qos invariant's
            # "every refusal is typed" is exactly this strict)
            refusal = f"{item.tag}: {e}"
            (d.surge_gold_refusals if gold else d.surge_bad_refusals).append(
                refusal
            )
            item.outcome = "rejected"
            item.detail = refusal
            return "refused"
        if gold:
            lat = time.monotonic() - t0
            d.surge_gold_lat_s.append(lat)
            d.surge_gold_lat_phase.setdefault(item.phase, []).append(lat)
        d.accepted += 1
        item.outcome = "pending"
        return "ok"

    for item in d.items:
        if item.phase == "trickle":
            attempt(item)
    time.sleep(1.5)  # let the control loop see the 1x baseline first
    retry: list[WorkItem] = []
    for item in d.items:
        if item.phase == "burst" and attempt(item) == "shed":
            retry.append(item)
    deadline = time.monotonic() + cfg.wait_timeout_s
    while retry and time.monotonic() < deadline:
        # the documented best-effort recourse: sleep the advertised
        # Retry-After (bounded — this is a drill, not a backoff study)
        pause = 0.3
        hints = [
            s["retry_after"] for s in d.surge_sheds if s.get("retry_after")
        ]
        if hints:
            pause = min(1.0, max(0.1, float(hints[-1])))
        time.sleep(pause)
        retry = [item for item in retry if attempt(item) == "shed"]
    for item in retry:
        item.outcome = "rejected"
        item.detail = "shed_best_effort past the retry deadline"


class _ScaleWatch:
    """Background sampler of the supervisor's (active, standby) split:
    records every transition with its wall-clock offset plus the peak
    active strength — the scale invariant's evidence that the fleet
    actually recruited through the burst and released after it."""

    def __init__(self, supervisor):
        import threading

        self.sup = supervisor
        self.transitions: list[dict] = []
        self.peak_active = 0
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._t = threading.Thread(
            target=self._run, name="drill-scale-watch", daemon=True
        )

    def start(self) -> None:
        self._t0 = time.monotonic()
        self._t.start()

    def stop(self) -> None:
        self._stop.set()
        self._t.join(timeout=5)

    def _run(self) -> None:
        last = None
        while not self._stop.wait(0.05):
            try:
                active, standby = self.sup.scale_counts()
            except Exception:  # noqa: BLE001 - sampling must not die
                continue
            self.peak_active = max(self.peak_active, active)
            if (active, standby) != last:
                last = (active, standby)
                self.transitions.append(
                    {
                        "t_s": round(time.monotonic() - self._t0, 3),
                        "active": active,
                        "standby": standby,
                    }
                )


def _p99(xs: list) -> float | None:
    if not xs:
        return None
    s = sorted(xs)
    return float(s[min(len(s) - 1, int(0.99 * len(s)))])


def _check_scale(
    d: "_Driller", fleet, watch: _ScaleWatch, released_back_s
) -> None:
    """The scale invariant (docs/FLEET.md "Autoscaling"), appended when
    ``--surge`` is armed:

    - the burst recruited the fleet to FULL strength (base + every
      standby slot) — a surge the loop slept through certifies nothing;
    - after the burst drained, the loop released back DOWN to the base
      strength within ``scale_wait_s`` (hysteresis + idle grace +
      cooldowns included);
    - both scale chaos points actually fired: one recruit was refused
      at the seam (and the loop still reached full strength — no armed
      cooldown after a failed recruit) and one release was steered onto
      the busiest worker (and no session was lost — covered by the
      standard invariants riding along).
    """
    d.extra_invariants.append("scale")
    full = d.cfg.workers + d.cfg.standby
    if watch.peak_active < full:
        d.violate(
            "scale",
            f"peak active strength {watch.peak_active} never reached "
            f"{full} (base {d.cfg.workers} + {d.cfg.standby} standby) — "
            f"the burst did not recruit the pool",
        )
    if released_back_s is None:
        active, standby = fleet.supervisor.scale_counts()
        d.violate(
            "scale",
            f"fleet still at {active} active / {standby} standby "
            f"{d.cfg.scale_wait_s:.0f}s after the burst drained — "
            f"never released back to base {d.cfg.workers}",
        )
    inj = d.injections_by_point()
    local = {p: sum(c.values()) for p, c in chaos.counts().items()}
    for point in ("scale.recruit.fail", "scale.release.race"):
        if inj.get(point, 0) + local.get(point, 0) < 1:
            d.violate(
                "scale",
                f"{point} never fired (injections: {inj}) — the seam "
                f"was not exercised; pick a seed that reaches it",
            )


def _check_qos(d: "_Driller") -> None:
    """The qos invariant (docs/SERVING.md "Tenant QoS"), appended when
    ``--surge`` is armed:

    - the burst actually reached the shed ladder (at least one typed
      best-effort shed, each carrying Retry-After);
    - every refusal the drill saw was TYPED ``shed_best_effort`` and
      landed on the best-effort tenant ONLY — the guaranteed tenant was
      never refused, never shed, never rate-limited;
    - guaranteed-tenant admission latency p99 stayed under
      ``qos_p99_bound_s`` THROUGH the burst — isolation, not just
      eventual admission.
    """
    d.extra_invariants.append("qos")
    if not d.surge_sheds:
        d.violate(
            "qos",
            "no best-effort shed ever fired — the burst never reached "
            "the shed ladder; raise --surge-factor",
        )
    for shed in d.surge_sheds:
        if not shed.get("retry_after"):
            d.violate(
                "qos",
                f"{shed['tag']}: shed_best_effort without a Retry-After "
                f"hint — the documented recourse is unplayable",
            )
            break
    for refusal in d.surge_gold_refusals:
        d.violate("qos", f"guaranteed tenant refused: {refusal}")
    for refusal in d.surge_bad_refusals:
        d.violate("qos", f"untyped or mis-tiered refusal: {refusal}")
    p99 = _p99(d.surge_gold_lat_s)
    if p99 is not None and p99 > d.cfg.qos_p99_bound_s:
        d.violate(
            "qos",
            f"guaranteed-tenant admission p99 {p99:.3f}s exceeds the "
            f"{d.cfg.qos_p99_bound_s:.1f}s bound — the burst leaked into "
            f"the guaranteed tier",
        )


class _RecycleWatch:
    """Background sampler of supervisor state: records every observed
    unready-recycle — a worker leaving READY and coming back under a
    BUMPED generation — with its wall-clock recovery time.  The governor
    drill's wedge evidence: the watchdog flipped readyz, the supervisor
    recycled, and how long the round trip took."""

    def __init__(self, supervisor, on_down=None):
        import threading

        self.sup = supervisor
        self.recycles: list[dict] = []
        # fired ONCE, on the first ready->down transition observed: the
        # governor drill disarms the wedge point in the inherited env
        # spec here, so RESPAWNED workers come up clean — without it
        # every fresh generation draws a fresh per-process schedule and
        # the wedge refires forever (an unbounded recycle storm instead
        # of one rescued wedge)
        self.on_down = on_down
        self._down_seen = False
        self._stop = threading.Event()
        self._t = threading.Thread(
            target=self._run, name="drill-recycle-watch", daemon=True
        )

    def start(self):
        self._t.start()

    def stop(self):
        self._stop.set()
        self._t.join(timeout=5)

    def _run(self):
        ready_gen: dict[str, int] = {}  # last generation observed READY
        down: dict[str, tuple[float, int]] = {}  # name -> (since, gen then)
        while not self._stop.wait(0.05):
            try:
                states = self.sup.states()
                gens = {w.name: w.generation for w in self.sup.workers}
            except Exception:  # noqa: BLE001 - sampling must not die
                continue
            now = time.monotonic()
            for name, state in states.items():
                gen = gens.get(name, 0)
                if state == "ready":
                    if name in down:
                        since, gen0 = down.pop(name)
                        if gen > gen0:  # came back as a NEW incarnation
                            self.recycles.append(
                                {
                                    "worker": name,
                                    "generation": gen,
                                    "recovery_s": now - since,
                                }
                            )
                    ready_gen[name] = gen
                elif name in ready_gen and name not in down:
                    down[name] = (now, ready_gen[name])
                    if not self._down_seen:
                        self._down_seen = True
                        if self.on_down is not None:
                            try:
                                self.on_down()
                            except Exception:  # noqa: BLE001
                                log.exception("drill: on_down hook failed")


def run_drill(cfg: DrillConfig) -> dict:
    """Run one seeded chaos drill; returns the summary record (also
    appended to ``cfg.summary_file`` when set).  ``summary["ok"]`` is the
    single pass/fail verdict; on failure the summary names the seed and
    plan digest that replay the run verbatim."""
    from tpu_life.fleet import Fleet, FleetConfig

    d = _Driller(cfg)
    spec = d.plan.spec()
    t_start = time.monotonic()
    prev_env = os.environ.get(chaos.ENV_VAR)
    os.environ[chaos.ENV_VAR] = json.dumps(spec)  # workers inherit this
    chaos.arm(d.plan)  # this process: router/supervisor/migrator seams
    workdir = cfg.workdir
    max_queue = 4 * (cfg.det_sessions + cfg.ising_sessions)
    if cfg.surge:
        # headroom above the WHOLE burst: the drill's shed ladder must
        # be exercised by the soft best-effort rung, never by hard
        # queue_full — a gold refusal at the hard rung is a qos failure
        max_queue = 4 * len(d.items)
    worker_args = [
        "--serve-backend", cfg.backend,
        "--capacity", str(cfg.capacity),
        "--chunk-steps", str(cfg.chunk_steps),
        "--max-queue", str(max_queue),
    ]
    if cfg.governor:
        # every worker runs the wedge watchdog: a wedged settle flips its
        # /readyz to 500 engine_wedged, and the supervisor's existing
        # unready-recycle + migration path is what the drill verifies
        worker_args += ["--settle-deadline", str(cfg.settle_deadline_s)]
    autoscale = None
    if cfg.surge:
        from tpu_life.fleet.autoscaler import AutoscaleConfig

        # drill-speed control loop: tight windows and cooldowns so the
        # whole recruit->release arc fits in CI seconds, burn-driven
        # scaling OFF (the drill's own sheds light the burn windows for
        # minutes — wall-clock the release-back must not wait on), and
        # the ceiling at exactly base + pool so "full strength" is a
        # deterministic number the scale invariant can assert
        autoscale = AutoscaleConfig(
            min_workers=cfg.workers,
            max_workers=cfg.workers + cfg.standby,
            depth_high=3.0,
            depth_low=0.5,
            window_s=5.0,
            cooldown_up_s=0.5,
            cooldown_down_s=2.0,
            idle_grace_s=1.5,
            scale_on_burn=False,
        )
        worker_args += [
            "--qos", _write_surge_policy(workdir),
            "--series-every", "0.25",
        ]
    fleet = Fleet(
        FleetConfig(
            workers=cfg.workers,
            port=0,
            worker_args=tuple(worker_args),
            log_dir=os.path.join(workdir, "logs"),
            spill_dir=os.path.join(workdir, "spill"),
            spill_every=cfg.spill_every,
            probe_interval_s=0.1,
            backoff_base_s=0.2,
            migrate_stuck_after_s=cfg.migrate_stuck_after_s,
            standby=cfg.standby if cfg.surge else 0,
            autoscale=autoscale,
            series_every_s=0.25 if cfg.surge else 1.0,
        )
    )
    d.fleet = fleet

    def _disarm_wedge_for_respawns() -> None:
        # the wedge did its damage (a worker just left READY): strip
        # engine.wedge from the INHERITED spec so respawned generations
        # come up clean — each fresh process draws a fresh per-process
        # schedule, and without this the wedge refires every generation
        # (an unbounded recycle storm, not one rescued wedge).  The live
        # processes' plans are untouched; only future spawns change.
        healed = {
            k: v
            for k, v in spec.get("points", {}).items()
            if k != "engine.wedge"
        }
        os.environ[chaos.ENV_VAR] = json.dumps(
            {"seed": spec["seed"], "points": healed}
        )
        log.info("chaos drill: engine.wedge disarmed for respawns")

    watch = (
        _RecycleWatch(fleet.supervisor, on_down=_disarm_wedge_for_respawns)
        if cfg.governor
        else None
    )
    scale_watch: _ScaleWatch | None = None
    released_back_s = None
    scale_summary: dict = {}
    try:
        fleet.start()
        if not fleet.wait_ready(timeout=120, min_workers=cfg.workers):
            raise RuntimeError(
                f"fleet never became ready: {fleet.supervisor.states()}"
            )
        if watch is not None:
            watch.start()
        d.base_url = f"http://127.0.0.1:{fleet.port}"
        client = GatewayClient(d.base_url, retries=8)
        if cfg.surge:
            scale_watch = _ScaleWatch(fleet.supervisor)
            scale_watch.start()
            _surge_submit(d)
        else:
            for item in d.items:
                d.submit_item(client, item)
        watchers: list[_StreamWatcher] = []
        if cfg.stream:
            # hang N live watchers on every accepted sid BEFORE the
            # kill lands: the whole point is that they ride through it
            for item in d.items:
                if item.sid is None:
                    continue
                for w in range(cfg.watchers_per_session):
                    watchers.append(
                        _StreamWatcher(d.base_url, item, f"{item.tag}.w{w}")
                    )
            for w in watchers:
                w.start()
        if not cfg.surge:
            # the surge drill's faults are the SCALE seams (a refused
            # recruit, a raced release) — its workers stay up; SIGKILLs
            # belong to the other drills
            d.run_kills(client)
        # poll everything to terminal; play the documented client
        # recourse for typed losses (resubmit from scratch, fresh sid)
        surge_clients = (
            {
                key: GatewayClient(d.base_url, api_key=key, retries=8)
                for key in (SURGE_GOLD_KEY, SURGE_FREE_KEY)
            }
            if cfg.surge
            else {}
        )
        for item in d.items:
            if item.sid is None:
                continue
            d.poll_until_terminal(client, item)
            while (
                item.outcome in ("lost", "failed")
                and item.resubmits < cfg.resubmit_lost
            ):
                item.resubmits += 1
                # resubmits stay IN tenant: a surge item re-enters as
                # the tenant it belongs to, never as the default
                sub = surge_clients.get(item.api_key, client)
                if not d.submit_item(sub, item):
                    break
                d.poll_until_terminal(client, item)
        for item in d.items:
            # EVERY workload item must deliver — including one whose
            # submission was rejected outright (sid None): a drill that
            # dropped work at admission must not certify itself ok
            if not item.delivered:
                d.violate(
                    "no_lost_work",
                    f"{item.tag} never yielded its oracle board "
                    f"(final: {item.outcome} {item.detail})",
                )
        if cfg.stream:
            join_deadline = time.monotonic() + cfg.wait_timeout_s
            for w in watchers:
                w.join(max(0.1, join_deadline - time.monotonic()))
        d._scrape_injections()
        d.check_metrics()
        if cfg.governor:
            d.recycles = list(watch.recycles)
            _check_governor(d, fleet)
        if cfg.stream:
            _check_stream(d, watchers)
        if cfg.surge:
            # the down leg: with every session terminal the demand is
            # gone — the loop must ride hysteresis + idle grace +
            # cooldowns back DOWN to base strength on its own
            rb0 = time.monotonic()
            while time.monotonic() < rb0 + cfg.scale_wait_s:
                active, _standby = fleet.supervisor.scale_counts()
                if active <= cfg.workers:
                    released_back_s = time.monotonic() - rb0
                    break
                time.sleep(0.1)
            scale_watch.stop()
            d._scrape_injections()  # the release leg's chaos evidence
            _check_scale(d, fleet, scale_watch, released_back_s)
            _check_qos(d)
            auto = fleet.supervisor.autoscaler
            scale_summary = {
                "base": cfg.workers,
                "standby_slots": cfg.standby,
                "peak_active": scale_watch.peak_active,
                "released_back_s": released_back_s,
                "transitions": scale_watch.transitions,
                "decisions": auto.decisions if auto is not None else 0,
            }
    finally:
        if watch is not None:
            watch.stop()
        if scale_watch is not None:
            scale_watch.stop()
        try:
            fleet.begin_drain()
            fleet.wait(timeout=60)
        finally:
            fleet.close()
            chaos.disarm()
            if prev_env is None:
                os.environ.pop(chaos.ENV_VAR, None)
            else:
                os.environ[chaos.ENV_VAR] = prev_env
    elapsed = time.monotonic() - t_start
    verdicts = d.verdicts()
    outcomes: dict[str, int] = {}
    for item in d.items:
        outcomes[item.outcome] = outcomes.get(item.outcome, 0) + 1
    recoveries = [
        k["recovery_s"] for k in d.kills if k.get("recovery_s") is not None
    ]
    done = outcomes.get("done", 0)
    if cfg.governor:
        kind = "governor_drill"
    elif cfg.stream:
        kind = "stream_drill"
    elif cfg.surge:
        kind = "surge_drill"
    else:
        kind = "chaos_drill"
    summary = {
        "kind": kind,
        # the replay stamp (docs/CHAOS.md): seed + canonical plan + its
        # digest — a failed CI drill is rerun locally from exactly these
        "seed": cfg.seed,
        "plan": spec,
        "plan_digest": d.plan.digest(),
        "workers": cfg.workers,
        "kills": d.kills,
        # governor mode: the wedge-recycle evidence (worker, successor
        # generation, readyz-500 -> ready-again wall seconds)
        **({"recycles": d.recycles} if cfg.governor else {}),
        # stream mode: the fan-out evidence — watcher count, total
        # frames observed, and how many watchers ended on a clean done
        **(
            {
                "stream": {
                    "watchers": len(watchers),
                    "frames_total": sum(len(w.frames) for w in watchers),
                    "ended_done": sum(
                        1
                        for w in watchers
                        if w.frames
                        and w.frames[-1].get("type") == "end"
                        and w.frames[-1].get("state") == "done"
                    ),
                }
            }
            if cfg.stream
            else {}
        ),
        # surge mode: the recruit->release arc and the tenant-isolation
        # evidence the scale/qos invariants judged
        **(
            {
                "scale": scale_summary,
                "qos": {
                    "sheds": len(d.surge_sheds),
                    "gold_submits": len(d.surge_gold_lat_s),
                    "gold_p99_s": _p99(d.surge_gold_lat_s),
                    "gold_p99_trickle_s": _p99(
                        d.surge_gold_lat_phase.get("trickle", [])
                    ),
                    "gold_p99_burst_s": _p99(
                        d.surge_gold_lat_phase.get("burst", [])
                    ),
                    "gold_refusals": d.surge_gold_refusals,
                    "bad_refusals": d.surge_bad_refusals,
                },
            }
            if cfg.surge
            else {}
        ),
        "sessions": len(d.items),
        "accepted": d.accepted,
        "outcomes": outcomes,
        "resubmits": sum(i.resubmits for i in d.items),
        "delivered": sum(1 for i in d.items if i.delivered),
        "injections": d.injections_by_point(),
        "injections_local": chaos.counts(),
        "migrations": getattr(d, "_migration_summary", {}).get("migrations", {}),
        "invariants": verdicts,
        "ok": all(v["ok"] for v in verdicts.values()),
        "recovery_s_max": max(recoveries) if recoveries else None,
        "elapsed_s": elapsed,
        "sessions_per_sec": done / elapsed if elapsed > 0 else 0.0,
    }
    if cfg.summary_file:
        from tpu_life import obs

        obs.ensure_parent(cfg.summary_file)
        with open(cfg.summary_file, "a") as f:
            f.write(json.dumps(summary) + "\n")
    return summary
