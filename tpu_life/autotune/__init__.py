"""Measured autotuning with a persistent per-device config cache.

The performance knobs (``backend``, ``block_steps``, ``local_kernel``,
``bitpack``, ``sync_every``) were hand-picked from one-off sweeps in
``experiments/``; this package makes the selection systematic, the way
production kernel stacks do it — an autotuner plus a persisted tuning DB:

- :func:`tune` — the **write path**: enumerate the legal candidate space
  for a :class:`TuneKey` (device kind + count, rule structure, padded
  board-shape bucket), run short warm+timed trials with median-of-k timing
  and per-candidate failure isolation, persist the winner to the JSON
  cache (``~/.cache/tpu_life/autotune.json``, ``TPU_LIFE_AUTOTUNE_CACHE``
  overrides).  Run offline via ``tpu-life tune``.
- :func:`resolve` — the **read path**: cache hit -> the tuned config; miss
  -> the analytic cost model (HBM-traffic / recomputed-fringe estimate,
  fitted to the committed blocksweep results).  **Never measures** — safe
  on every latency-sensitive path (the serve engine resolves through it
  per CompileKey).

Integration points: ``RunConfig(backend="tuned", tune_mode=...)`` in the
driver, ``ServeConfig(backend="tuned")`` in the serving stack, and the
``tpu-life tune`` CLI mode.  See docs/AUTOTUNE.md.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_life.autotune import cache, cost_model, runner, space
from tpu_life.autotune.runner import (
    TrialResult,
    best_result,
    reset_trial_count,
    run_trials,
    trial_count,
)
from tpu_life.autotune.space import (
    TuneKey,
    TunedConfig,
    default_backend_set,
    enumerate_candidates,
    tune_key_for,
    tuned_record,
)
from tpu_life.models.rules import Rule, get_rule

TUNE_MODES = ("off", "cache", "measure")

__all__ = [
    "TuneKey",
    "TunedConfig",
    "TuneResult",
    "TrialResult",
    "TUNE_MODES",
    "tune",
    "resolve",
    "resolve_backend_kwargs",
    "tuned_record",
    "tune_key_for",
    "enumerate_candidates",
    "default_backend_set",
    "trial_count",
    "reset_trial_count",
    "cache",
    "cost_model",
    "runner",
    "space",
]


@dataclass
class TuneResult:
    """What one tuning search did: the full trial table plus the winner."""

    key: TuneKey
    results: list[TrialResult]
    best: TunedConfig
    source: str  # "measured" | "cost_model" (dry runs never measure)
    cache_file: str | None  # where the winner was persisted (None: not saved)


def resolve(
    key: TuneKey,
    *,
    mode: str = "cache",
    shape: tuple[int, int] | None = None,
    backend_set=None,
    cache_file=None,
) -> tuple[TunedConfig, str]:
    """The read path: ``(config, source)`` with source in
    ``{"cache", "cost_model"}``.  Never measures, regardless of mode —
    ``mode="off"`` additionally skips the cache read (pure cost model),
    ``mode="measure"`` is the *caller's* cue to run :func:`tune` on a
    miss (the driver does; the serve engine deliberately does not).
    """
    if mode not in TUNE_MODES:
        raise ValueError(f"tune_mode must be one of {TUNE_MODES}, got {mode!r}")
    if mode != "off":
        entry = cache.get(key, path=cache_file)
        if entry is not None:
            return TunedConfig.from_dict(entry["config"]), "cache"
    candidates = enumerate_candidates(key, backend_set=backend_set, shape=shape)
    return cost_model.choose(key, candidates), "cost_model"


def resolve_backend_kwargs(
    rule,
    shape: tuple[int, int],
    kwargs: dict,
    *,
    mode: str = "cache",
    cache_file=None,
) -> tuple[str, TunedConfig, str]:
    """Resolve the ``"tuned"`` pseudo-backend for a ``get_backend`` call
    site: tuned knobs fill into ``kwargs`` via ``setdefault``, so any knob
    the caller already pinned (an explicit flag) wins over the cache.

    The single merge rule shared by ``bench.py`` and the CLI bench —
    returns ``(backend_name, tuned_config, source)``; read path only.
    """
    if isinstance(rule, str):
        rule = get_rule(rule)
    key = tune_key_for(rule, shape)
    tuned, source = resolve(key, mode=mode, shape=shape, cache_file=cache_file)
    for k, v in tuned.backend_kwargs().items():
        kwargs.setdefault(k, v)
    return tuned.backend, tuned, source


def tune(
    key: TuneKey,
    rule: Rule | str | None = None,
    *,
    shape: tuple[int, int] | None = None,
    board: np.ndarray | None = None,
    backend_set=None,
    trials: int = 3,
    steps: int | None = None,
    warmup_steps: int | None = None,
    dry_run: bool = False,
    save: bool = True,
    cache_file=None,
    measure=None,
    on_trial=None,
) -> TuneResult:
    """The write path: search the candidate space for ``key``, persist the
    winner.  ``dry_run`` ranks by the cost model alone (no device touched,
    nothing persisted) — the CI smoke path.

    The trial board defaults to a seeded random board of ``shape`` (the
    key's bucket when unset), so tuning needs no input files and a re-tune
    measures the identical workload.
    """
    if rule is None:
        rule = key.rule_name
    if isinstance(rule, str):
        rule = get_rule(rule)
    shape = tuple(shape) if shape is not None else key.shape_bucket
    candidates = enumerate_candidates(key, backend_set=backend_set, shape=shape)
    if dry_run:
        results = [
            TrialResult(c, cost_model.estimate_cost(key, c)) for c in candidates
        ]
        best = cost_model.choose(key, candidates)
        return TuneResult(key, results, best, "cost_model", None)
    if board is None:
        board = runner.make_trial_board(key, shape)
    results = run_trials(
        key,
        candidates,
        board,
        rule,
        trials=trials,
        steps=steps,
        warmup_steps=warmup_steps,
        measure=measure,
        on_trial=on_trial,
    )
    win = best_result(results)
    if win is None:
        errors = "; ".join(
            f"{r.config.describe()}: {r.error}" for r in results[:4]
        )
        raise RuntimeError(
            f"every candidate failed for {key.id()} — first errors: {errors}"
        )
    saved = None
    if save:
        cache.put(
            key,
            win.config,
            source="measured",
            seconds_per_step=win.seconds_per_step,
            trials=trials,
            path=cache_file,
        )
        saved = str(cache.cache_path(cache_file))
    return TuneResult(key, results, win.config, "measured", saved)
