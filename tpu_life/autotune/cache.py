"""The persistent tuning DB: one JSON file of TuneKey -> TunedConfig.

Production kernel stacks persist their autotune results (the Ising-on-TPU
per-topology kernel tables are the same shape); here the store is a single
JSON file so it is inspectable, diffable, and shippable:

- **location**: ``~/.cache/tpu_life/autotune.json`` (respects
  ``XDG_CACHE_HOME``), overridable via ``TPU_LIFE_AUTOTUNE_CACHE`` — tests
  and CI point it at a tmpdir, a fleet can bake a pre-tuned file into an
  image;
- **atomic writes**: serialize to a sibling temp file, ``os.replace`` into
  place — a reader never sees a torn file, a crashed writer leaves the old
  contents intact;
- **schema versioning**: the file carries ``schema``; a mismatch (older or
  newer writer) invalidates the whole file — tuned numbers measured under
  different key/config semantics must not leak forward.  Individually
  malformed entries are dropped on read for the same reason.

Corrupt or unreadable files degrade to an empty cache (the cost model
covers the miss); the cache is an accelerator, never a failure source.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time
from pathlib import Path

try:
    import fcntl
except ImportError:  # non-POSIX: degrade to best-effort (no lock)
    fcntl = None

from tpu_life.autotune.space import TuneKey, TunedConfig

SCHEMA_VERSION = 1
ENV_VAR = "TPU_LIFE_AUTOTUNE_CACHE"


def cache_path(path: str | os.PathLike | None = None) -> Path:
    """Resolve the cache file path: explicit arg > env var > XDG default."""
    if path is not None:
        return Path(path)
    env = os.environ.get(ENV_VAR)
    if env:
        return Path(env)
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return Path(base) / "tpu_life" / "autotune.json"


def load(path: str | os.PathLike | None = None) -> dict:
    """The cache's entry dict (``key.id() -> entry``); {} on any problem.

    A wrong ``schema`` discards the file wholesale; an entry that does not
    round-trip through :class:`TunedConfig` is dropped individually.
    """
    p = cache_path(path)
    try:
        raw = json.loads(p.read_text())
    except (OSError, ValueError):
        return {}
    if not isinstance(raw, dict) or raw.get("schema") != SCHEMA_VERSION:
        return {}
    entries = raw.get("entries")
    if not isinstance(entries, dict):
        return {}
    good: dict = {}
    for kid, entry in entries.items():
        try:
            TunedConfig.from_dict(entry["config"])  # validates shape
            good[kid] = entry
        except (KeyError, TypeError, ValueError):
            continue  # stale/malformed entry: invalidated, not fatal
    return good


@contextlib.contextmanager
def _locked(path: str | os.PathLike | None):
    """Advisory exclusive lock (a ``.lock`` sibling) serializing the
    read-modify-write cycles of :func:`put` / :func:`invalidate`: the
    atomic replace prevents *torn* files but not *lost updates* — two
    concurrent tuners would otherwise each publish a full file holding
    only their own view, and the last writer silently drops the first
    writer's freshly measured entry.  Degrades to best-effort where
    locking is unavailable (non-POSIX, odd filesystems)."""
    p = cache_path(path)
    if fcntl is None:
        yield
        return
    try:
        p.parent.mkdir(parents=True, exist_ok=True)
        f = open(p.with_name(p.name + ".lock"), "w")
    except OSError:
        yield
        return
    try:
        with contextlib.suppress(OSError):
            fcntl.flock(f, fcntl.LOCK_EX)
        yield
    finally:
        f.close()  # releases the flock


def _write(entries: dict, path: str | os.PathLike | None = None) -> Path:
    """Atomically replace the cache file with ``entries``."""
    p = cache_path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    payload = json.dumps(
        {"schema": SCHEMA_VERSION, "entries": entries}, indent=1, sort_keys=True
    )
    fd, tmp = tempfile.mkstemp(
        prefix=p.name + ".", suffix=".tmp", dir=str(p.parent)
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return p


def get(key: TuneKey, path: str | os.PathLike | None = None) -> dict | None:
    """The cached entry for ``key``, or None on a miss."""
    return load(path).get(key.id())


def put(
    key: TuneKey,
    config: TunedConfig,
    *,
    source: str,
    seconds_per_step: float | None = None,
    trials: int | None = None,
    path: str | os.PathLike | None = None,
) -> dict:
    """Record ``config`` as the tuned decision for ``key`` (read-modify-
    write of the whole file, atomic publish); returns the entry written.

    ``source`` records provenance ("measured" / "cost_model") so a perf
    artifact resolved from this entry can say where its numbers came from.
    """
    entry = {
        "key": key.to_dict(),
        "config": config.to_dict(),
        "source": source,
        "seconds_per_step": seconds_per_step,
        "trials": trials,
        "tuned_at": time.time(),
    }
    with _locked(path):
        entries = load(path)
        entries[key.id()] = entry
        _write(entries, path)
    return entry


def invalidate(key: TuneKey | None = None, path: str | os.PathLike | None = None) -> int:
    """Drop one key's entry (or every entry when ``key`` is None);
    returns how many entries were removed."""
    with _locked(path):
        entries = load(path)
        if key is None:
            n = len(entries)
            entries = {}
        else:
            n = 1 if entries.pop(key.id(), None) is not None else 0
        _write(entries, path)
    return n
