"""Analytic cost fallback: rank candidates without touching a device.

The read path (``autotune.resolve``) must never measure — serving latency
cannot pay tuning cost, and a cache miss on a fresh machine still needs an
answer.  This model estimates *relative cost per cell-update* from the two
effects the committed sweeps isolated:

- **HBM traffic** amortizes over the deep-halo blocking factor ``k``: one
  board read + write per ``k``-step block, so the per-step traffic term is
  ``TRAFFIC / k`` (x8 for unpacked int8 boards vs the bit-sliced layout);
- **recomputed fringe** grows with ``k``: each blocked step recomputes a
  halo ring ``radius`` deeper than the last, so the per-step overhead term
  is ``FRINGE * radius * k``.

``cost(k) = COMPUTE + TRAFFIC/k + FRINGE * radius * k`` with constants
fitted to experiments/RESULTS_blocksweep_r4.json (normalized inverse
throughput of the composed sharded+pallas path at 16384^2 Conway, k in
{4,8,16,32,64}): the fit puts the minimum in the k=8..16 noise band and
reproduces the measured monotone degradation at k >= 32 — the cliff the
sweep found (k=64 ran 26% slower than k=8).  Absolute numbers are
meaningless (the chip's window wobbles +-20%); only the ordering is used.
"""

from __future__ import annotations

from tpu_life.autotune.space import TuneKey, TunedConfig

# fitted to RESULTS_blocksweep_r4.json (see module docstring): relative
# per-cell-update cost = COMPUTE + TRAFFIC/k + FRINGE * radius * k
COMPUTE = 0.837
TRAFFIC = 0.795
FRINGE = 0.008

# unpacked int8 boards move 8x the bytes of the bit-sliced layout
# (32 cells/uint32 word vs 8 cells/8 bytes — backends/jax_backend.py)
UNPACKED_TRAFFIC_FACTOR = 8.0

# per-backend structural overheads, relative to the blocked sharded path:
# jax has no deep-halo blocking (every step is one HBM pass, k == 1);
# pallas == sharded-at-n=1 (same VMEM blocking trade); numpy is the truth
# executor, ~3 orders off any compiled path
NUMPY_PENALTY = 1000.0

# defaults a backend applies when block_steps is None (mirrors each
# backend's own default: sharded XLA exchanges every step, the Pallas
# deep-halo kernels block 8 steps per HBM pass)
_DEFAULT_K = {"jax": 1, "sharded": 1, "pallas": 8}


def effective_block_steps(cfg: TunedConfig) -> int:
    if cfg.backend == "jax" or cfg.backend == "numpy":
        return 1  # no deep-halo blocking: one HBM pass per step
    if cfg.block_steps is not None:
        return max(1, cfg.block_steps)
    if cfg.backend == "sharded" and cfg.local_kernel == "pallas":
        return 8  # the Pallas local kernel's own deep-halo default
    return _DEFAULT_K.get(cfg.backend, 1)


def estimate_cost(key: TuneKey, cfg: TunedConfig) -> float:
    """Relative cost per cell-update of ``cfg`` in situation ``key``
    (lower is better; only the ordering is meaningful)."""
    if cfg.backend == "numpy":
        return NUMPY_PENALTY
    k = effective_block_steps(cfg)
    traffic = TRAFFIC
    if not (cfg.bitpack and key.bitpack_ok):
        traffic *= UNPACKED_TRAFFIC_FACTOR
    cost = COMPUTE + traffic / k + FRINGE * key.radius * k
    if cfg.backend == "sharded" and key.device_count > 1:
        # per-chip throughput holds ~parity with the single-chip kernel
        # (BASELINE.md parity leg), so total cost divides by the mesh —
        # with a small halo-exchange tax per extra device ring
        cost = cost / key.device_count + 0.02
    if cfg.backend in ("pallas", "sharded") and cfg.local_kernel == "pallas":
        # measured: the compiled deep-halo kernel edges out the XLA scan
        # at equal k (RESULTS_blocksweep_r4_confirm.json) — a nudge, so a
        # *measured* XLA win still beats an assumed Pallas one
        cost *= 0.97
    if cfg.backend == "jax" and cfg.stencil != "auto":
        # the stencil axis (docs/RULES.md): the analytic view mirrors
        # resolve_stencil's crossover model — banded matmuls win past
        # the crossover radius (and always on weighted/continuous
        # kernels, where the roll path unrolls O(r^2) shifted adds); a
        # measured trial still overrides this ordering
        from tpu_life.ops.conv import CROSSOVER_RADIUS

        wide = key.continuous or key.radius >= CROSSOVER_RADIUS
        if cfg.stencil == "matmul":
            cost *= 0.85 if wide else 1.5
    return cost


def choose(key: TuneKey, candidates: list[TunedConfig]) -> TunedConfig:
    """The cost model's pick: argmin cost, first-wins on exact ties so the
    choice is deterministic for a fixed candidate order."""
    if not candidates:
        raise ValueError("choose() needs at least one candidate")
    return min(candidates, key=lambda c: estimate_cost(key, c))
