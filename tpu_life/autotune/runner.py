"""Measured trials: time each candidate on the live device, pick the min.

Methodology matches ``bench.py`` in spirit but is budgeted for a search
loop rather than one armored headline capture: per candidate the board is
staged once (the ``make_runner`` seam — the same path the driver runs),
one warmup advance absorbs compilation, then ``trials`` timed advances are
taken and the **median** seconds/step reported — the median rides out the
chip's window-to-window wobble better than the mean over so few samples.

Failure isolation is per candidate: a candidate whose backend refuses to
construct (mesh divisibility, kernel constraints) or crashes mid-trial is
recorded as infeasible with its error string and the search continues —
one broken configuration must never abort the sweep that would route
around it.

``trial_count()`` is the measurement probe: every timed trial the process
runs increments it, so tests (and the serve read path's never-measure
guarantee) can assert exactly how many device measurements an operation
performed.
"""

from __future__ import annotations

import statistics
import time
from dataclasses import dataclass, field

import numpy as np

from tpu_life import obs
from tpu_life.autotune.space import TuneKey, TunedConfig
from tpu_life.models.rules import Rule

# measurement probe (see module docstring); mutable holder so callers keep
# a live view through the module, not a stale int import
_MEASURED = {"trials": 0}


def trial_count() -> int:
    """Timed trials this process has run (the never-measure probe)."""
    return _MEASURED["trials"]


def reset_trial_count() -> None:
    _MEASURED["trials"] = 0


@dataclass
class TrialResult:
    config: TunedConfig
    seconds_per_step: float | None  # None => infeasible
    error: str | None = None
    samples: list[float] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.seconds_per_step is not None


def make_trial_board(key: TuneKey, shape: tuple[int, int]) -> np.ndarray:
    """A representative random board: ~50% density, seeded so every
    candidate (and every re-tune) measures the same workload."""
    rng = np.random.default_rng(0)
    h, w = shape
    board = rng.integers(0, 2, size=(h, w), dtype=np.int8)
    if key.states > 2:
        board *= rng.integers(1, key.states, size=(h, w), dtype=np.int8)
    return board


def _trial_runner_kwargs(rule: Rule) -> dict:
    """Per-rule ``make_runner`` extras for a trial.

    Stochastic rules consume the counter-based PRNG state: a fixed seed
    keeps every candidate (and every re-tune) on the same workload, and
    ising needs a temperature — measured at the critical point, the
    hardest-mixing (most acceptance-table-consulting) regime, so the
    tuned pick is honest for the worst case.
    """
    if not getattr(rule, "stochastic", False):
        return {}
    kw: dict = {"seed": 0}
    from tpu_life.models.rules import IsingRule

    if isinstance(rule, IsingRule):
        from tpu_life.mc.ising import T_CRITICAL

        kw["temperature"] = T_CRITICAL
    return kw


def _measure(
    cfg: TunedConfig,
    board: np.ndarray,
    rule: Rule,
    *,
    steps: int,
    warmup_steps: int,
    trials: int,
) -> tuple[float, list[float]]:
    """(median seconds/step, raw samples) of one candidate on the device."""
    from tpu_life.backends.base import get_backend, make_runner

    backend = get_backend(cfg.backend, rule=rule, **cfg.backend_kwargs())
    runner = make_runner(backend, board, rule, **_trial_runner_kwargs(rule))
    runner.advance(warmup_steps)  # absorbs compilation + staging
    runner.sync()
    samples: list[float] = []
    for _ in range(max(1, trials)):
        _MEASURED["trials"] += 1
        t0 = time.perf_counter()
        runner.advance(steps)
        runner.sync()
        samples.append((time.perf_counter() - t0) / steps)
    return statistics.median(samples), samples


def default_trial_steps(device_kind: str) -> tuple[int, int]:
    """(steps per timed trial, warmup steps).  TPU trials need enough steps
    that the fused work dwarfs per-dispatch tunnel jitter; CPU trials at
    4096^2 are compute-bound at a handful of steps."""
    return (64, 16) if device_kind == "tpu" else (4, 2)


def run_trials(
    key: TuneKey,
    candidates: list[TunedConfig],
    board: np.ndarray,
    rule: Rule,
    *,
    trials: int = 3,
    steps: int | None = None,
    warmup_steps: int | None = None,
    measure=None,
    on_trial=None,
) -> list[TrialResult]:
    """Measure every candidate; infeasible ones are recorded, never raised.

    ``measure`` injects a fake timing function for tests
    (``measure(cfg, board, rule) -> seconds_per_step``); ``on_trial`` is a
    progress callback ``(index, total, TrialResult)`` for the CLI table.
    """
    d_steps, d_warm = default_trial_steps(key.device_kind)
    steps = d_steps if steps is None else steps
    warmup_steps = d_warm if warmup_steps is None else warmup_steps
    results: list[TrialResult] = []
    for i, cfg in enumerate(candidates):
        # a span per candidate: a traced `run --tune-mode measure` (or
        # `tpu-life tune` under tracing) shows where the search time went
        with obs.span("autotune.trial", candidate=cfg.describe()):
            try:
                if measure is not None:
                    sps = float(measure(cfg, board, rule))
                    res = TrialResult(cfg, sps, samples=[sps])
                else:
                    sps, samples = _measure(
                        cfg,
                        board,
                        rule,
                        steps=steps,
                        warmup_steps=warmup_steps,
                        trials=trials,
                    )
                    res = TrialResult(cfg, sps, samples=samples)
            except Exception as e:  # noqa: BLE001 — per-candidate isolation
                res = TrialResult(cfg, None, error=f"{type(e).__name__}: {e}")
        results.append(res)
        if on_trial is not None:
            on_trial(i, len(candidates), res)
    return results


def best_result(results: list[TrialResult]) -> TrialResult | None:
    """The winner: minimum median seconds/step over feasible results,
    first-wins on exact ties (deterministic for a fixed candidate order).
    None when every candidate was infeasible."""
    ok = [r for r in results if r.ok]
    if not ok:
        return None
    return min(ok, key=lambda r: r.seconds_per_step)
