"""The tuning space: what a tuned configuration is, and which ones are legal.

The config surface has a dozen performance-critical knobs (``RunConfig``)
whose best values depend on device kind, rule shape, and board geometry —
the blocksweep study (experiments/RESULTS_blocksweep_r4.json) showed the
deep-halo blocking factor alone swings throughput ~35% and that its optimum
is device- and radius-dependent.  This module defines the two value types
the autotuner trades in:

- :class:`TuneKey` — the *situation*: device kind + count, rule structure
  (name, radius, states, neighborhood, boundary), the padded board-shape
  bucket, and bit-slicing eligibility.  Two runs with equal keys want the
  same knobs, so the key is the unit of cache identity.
- :class:`TunedConfig` — the *decision*: backend, ``block_steps``,
  ``local_kernel``, ``bitpack``, ``sync_every`` — exactly the RunConfig
  knobs the measured sweeps showed matter.

``enumerate_candidates`` produces the legal cross-product for a key,
reusing each backend's own constraints (Pallas compiles only on TPU,
``local_kernel='pallas'`` needs the packed 1-D-mesh path, torus boards
need exact row divisibility) so a candidate that cannot construct is
never proposed in the first place.  ``runner.run_trials`` still isolates
per-candidate failures — constraints here are an optimization, not the
safety net.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from tpu_life.models.rules import IsingRule, Rule

# block_steps grid: brackets the measured optimum (k=8, blocksweep r4) and
# includes the degradation region (k>=32) so a measured sweep re-verifies
# the cliff on new hardware instead of assuming it
BLOCK_STEPS_GRID = (1, 4, 8, 16, 32)

# shape buckets never go below one TPU tile in either dimension: configs
# don't change meaningfully inside a tile, and tiny boards would otherwise
# explode the cache with one entry per toy shape
MIN_BUCKET = 128


@dataclass(frozen=True)
class TuneKey:
    """Cache identity: everything the best config is allowed to depend on."""

    device_kind: str  # jax platform of the target devices ("cpu" / "tpu")
    device_count: int
    rule_name: str
    radius: int
    states: int
    neighborhood: str  # "moore" | "von_neumann"
    boundary: str  # "clamped" | "torus"
    shape_bucket: tuple[int, int]  # padded (h, w) bucket, power-of-two ceil
    bitpack_ok: bool  # bit-sliced path eligible for this rule family
    # stochastic (Monte-Carlo) rules tune a different candidate space:
    # only the key-schedule executors are legal, and "bitpack" means the
    # packed Metropolis engine (tpu_life.mc.packed), not the life-like
    # adder tree.  Kept out of id() for deterministic keys so every
    # pre-existing cache entry stays addressable.
    stochastic: bool = False
    # continuous (weighted-kernel float32) rules — the Lenia tier: only
    # the float executors are legal, and the candidate axis that matters
    # is the stencil (roll shift-adds vs banded matmuls).  Kept out of
    # id() for discrete keys, like `stochastic`.
    continuous: bool = False

    def id(self) -> str:
        """Stable string form — the JSON cache's entry key."""
        h, w = self.shape_bucket
        return (
            f"{self.device_kind}x{self.device_count}"
            f"|{self.rule_name}|r{self.radius}s{self.states}"
            f"|{self.neighborhood}|{self.boundary}"
            f"|{h}x{w}|bp{int(self.bitpack_ok)}"
            + ("|mc" if self.stochastic else "")
            + ("|cc" if self.continuous else "")
        )

    def to_dict(self) -> dict:
        d = asdict(self)
        d["shape_bucket"] = list(self.shape_bucket)
        return d


@dataclass(frozen=True)
class TunedConfig:
    """The knob settings a key resolves to — a RunConfig fragment."""

    backend: str
    block_steps: int | None = None  # None keeps the backend's own default
    local_kernel: str = "auto"  # sharded backend only
    bitpack: bool = True
    sync_every: int = 0  # 0 = one fused run (never swept; host-sync cadence
    # belongs to snapshots/metrics, not throughput)
    # the neighborhood-counting path (docs/RULES.md): the measured
    # stencil axis — "auto" (pre-existing cache entries; the analytic
    # crossover model applies), "roll", or "matmul".  Only the jax
    # executor honors it today; sharded/pallas carry their own kernels.
    stencil: str = "auto"

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TunedConfig":
        return cls(
            backend=str(d["backend"]),
            block_steps=None if d.get("block_steps") is None else int(d["block_steps"]),
            local_kernel=str(d.get("local_kernel", "auto")),
            bitpack=bool(d.get("bitpack", True)),
            sync_every=int(d.get("sync_every", 0)),
            stencil=str(d.get("stencil", "auto")),
        )

    def backend_kwargs(self) -> dict:
        """kwargs for ``get_backend`` realizing this decision.  Backends
        tolerate unknown kwargs (``**_``), so the full set always passes."""
        kw: dict = {"bitpack": self.bitpack, "local_kernel": self.local_kernel}
        if self.block_steps is not None:
            kw["block_steps"] = self.block_steps
        if self.stencil != "auto":
            kw["stencil"] = self.stencil
        return kw

    def describe(self) -> str:
        k = "-" if self.block_steps is None else str(self.block_steps)
        return (
            f"{self.backend} k={k} local_kernel={self.local_kernel} "
            f"bitpack={int(self.bitpack)} sync_every={self.sync_every} "
            f"stencil={self.stencil}"
        )


def tuned_record(backend: str, kwargs: dict) -> dict:
    """The BENCH-record ``"tuned"`` payload: the knob set a ``get_backend``
    call site actually ran, in the TunedConfig schema — one source of
    truth for the bench/CLI perf records, so the record fields cannot
    drift from the cache schema."""
    return TunedConfig(
        backend=backend,
        block_steps=kwargs.get("block_steps"),
        local_kernel=kwargs.get("local_kernel") or "auto",
        bitpack=bool(kwargs.get("bitpack", True)),
        sync_every=int(kwargs.get("sync_every", 0)),
        stencil=kwargs.get("stencil") or "auto",
    ).to_dict()


def shape_bucket(height: int, width: int) -> tuple[int, int]:
    """Pad each dimension up to the next power of two (floor MIN_BUCKET).

    Boards inside one bucket share halo/traffic proportions closely enough
    that one tuned config serves them all; the bucket also bounds cache
    cardinality to ~log^2 of the shape space.
    """

    def up(n: int) -> int:
        b = MIN_BUCKET
        while b < n:
            b <<= 1
        return b

    if height < 1 or width < 1:
        raise ValueError(f"board shape must be positive, got {height}x{width}")
    return up(height), up(width)


def _bitpack_eligible(rule: Rule) -> bool:
    """Bit-sliced path eligibility from rule structure alone (mirrors
    ``bitlife.supports_family`` + the diamond/torus variants, and the
    stochastic tier's ``mc.packed_supports``) — kept import-light so key
    construction never needs jax."""
    if getattr(rule, "continuous", False):
        return False  # float boards have no bitplane form
    if getattr(rule, "stochastic", False):
        # the packed Metropolis engine (tpu_life.mc.packed): ising only —
        # noisy rules keep the int8 roll composition
        return isinstance(rule, IsingRule)
    if rule.states != 2 or rule.include_center:
        return False
    if rule.neighborhood == "moore":
        return rule.radius == 1  # clamped and torus both run packed
    # von Neumann diamond: 4 count planes => radius <= 2, clamped only
    return rule.boundary == "clamped" and rule.radius <= 2


def tune_key_for(
    rule: Rule,
    shape: tuple[int, int],
    *,
    device_kind: str | None = None,
    device_count: int | None = None,
) -> TuneKey:
    """Build the key for tuning ``rule`` on a ``shape`` board.

    Device kind/count default to the live jax platform — the only part of
    the key that touches the runtime, overridable so tests and offline
    tooling can build keys for hardware they are not on.
    """
    if device_kind is None or device_count is None:
        import jax

        devices = jax.devices()
        device_kind = device_kind or devices[0].platform
        device_count = device_count or len(devices)
    h, w = int(shape[0]), int(shape[1])
    return TuneKey(
        device_kind=str(device_kind),
        device_count=int(device_count),
        rule_name=rule.name,
        radius=rule.radius,
        states=rule.states,
        neighborhood=rule.neighborhood,
        boundary=rule.boundary,
        shape_bucket=shape_bucket(h, w),
        bitpack_ok=_bitpack_eligible(rule),
        stochastic=bool(getattr(rule, "stochastic", False)),
        continuous=bool(getattr(rule, "continuous", False)),
    )


def default_backend_set(device_kind: str) -> tuple[str, ...]:
    """Backends worth measuring on this device kind.  Pallas compiles only
    on TPU (interpret mode elsewhere is Python-speed — measuring it would
    just burn the trial budget); numpy is the truth executor, never a
    performance candidate."""
    if device_kind == "tpu":
        return ("jax", "sharded", "pallas")
    return ("jax", "sharded")


def enumerate_candidates(
    key: TuneKey,
    *,
    backend_set: tuple[str, ...] | list[str] | None = None,
    shape: tuple[int, int] | None = None,
) -> list[TunedConfig]:
    """The legal candidate list for ``key``, in deterministic order.

    Each backend contributes the knob combinations it actually honors:

    - ``jax``: no blocking knobs — one candidate (plus the unpacked int8
      variant when the rule is bitpack-eligible, so a measured sweep can
      re-verify the packed path wins rather than assume it);
    - ``sharded``: ``block_steps`` grid x ``local_kernel`` (the Pallas
      stripe kernel only on TPU packed 1-D clamped boards — mirroring
      ``bench.default_tpu_local_kernel``); torus rules drop out entirely
      when the exact ``shape`` rows don't divide the mesh;
    - ``pallas``: ``block_steps`` grid, TPU only (the compiled kernel).

    ``shape`` is the exact board shape when known — used only for
    feasibility checks that depend on exact (not bucketed) geometry.
    """
    backends = tuple(backend_set or default_backend_set(key.device_kind))
    on_tpu = key.device_kind == "tpu"
    if key.continuous:
        # continuous keys: only the float executors are legal, and the
        # axis that matters is the stencil — both offered so a measured
        # sweep verifies the matmul (MXU) win instead of assuming it
        return [
            TunedConfig("jax", None, "auto", False, 0, "matmul"),
            TunedConfig("jax", None, "auto", False, 0, "roll"),
        ]
    if key.stochastic:
        # stochastic keys: only the key-schedule executors are legal
        # (mc.SUPPORTED_BACKENDS), and the knob that matters is the packed
        # Metropolis engine vs the int8 roll path — both offered when the
        # rule is packed-eligible so a measured sweep verifies the packed
        # win instead of assuming it.  Sharded/pallas would be a typed
        # rejection downstream; never propose them.
        out = [TunedConfig("jax", None, "auto", key.bitpack_ok, 0)]
        if key.bitpack_ok:
            out.append(TunedConfig("jax", None, "auto", False, 0))
        return out
    out: list[TunedConfig] = []
    for backend in backends:
        if backend == "jax":
            out.append(TunedConfig("jax", None, "auto", key.bitpack_ok, 0))
            if key.bitpack_ok:
                out.append(TunedConfig("jax", None, "auto", False, 0))
            if key.radius > 1:
                # the stencil axis (docs/RULES.md): at radius > 1 the
                # banded-matmul counting path is a real contender —
                # offer both so the crossover is measured, not guessed
                out.append(
                    TunedConfig("jax", None, "auto", False, 0, "matmul")
                )
                out.append(
                    TunedConfig("jax", None, "auto", False, 0, "roll")
                )
        elif backend == "sharded":
            if key.boundary == "torus":
                h = shape[0] if shape is not None else key.shape_bucket[0]
                if h % key.device_count != 0:
                    continue  # exact rows must divide the mesh — infeasible
            kernels = ["xla"]
            if (
                on_tpu
                and key.bitpack_ok
                and key.boundary == "clamped"
                and key.neighborhood == "moore"
            ):
                kernels.append("pallas")
            for kernel in kernels:
                for k in BLOCK_STEPS_GRID:
                    out.append(
                        TunedConfig("sharded", k, kernel, key.bitpack_ok, 0)
                    )
        elif backend == "pallas":
            if not on_tpu:
                continue  # interpret mode: correctness path, not a candidate
            if key.boundary == "torus" and not key.bitpack_ok:
                continue  # no int8 torus kernel
            for k in BLOCK_STEPS_GRID:
                out.append(TunedConfig("pallas", k, "auto", key.bitpack_ok, 0))
        elif backend == "numpy":
            out.append(TunedConfig("numpy", None, "auto", False, 0))
        else:
            raise ValueError(f"unknown backend {backend!r} in backend_set")
    if not out:
        raise ValueError(
            f"no feasible candidates for {key.id()} with backends {backends}"
        )
    return out
